"""ISSUE 10: the service write-ahead journal — record round trip, torn
lines, crash-resume bitwise pins, duplicate-tell idempotency, quota
grandfathering, compaction, and the real-SIGKILL subprocess resume.
"""

import json
import os
import signal
import subprocess
import sys

import pytest

from hyperopt_tpu import hp
from hyperopt_tpu.service import StudyJournal, StudyQuotaError, StudyScheduler
from hyperopt_tpu.service.journal import JournalError, wal_path_for

SPACE = {"x": hp.uniform("x", -5, 5)}
SPEC = {"space": {"x": {"dist": "uniform", "args": [-5, 5]}}}


def _drive(sched, sid, n):
    """n serial ask->tell rounds; returns [(tid, repr(x))] (repr is the
    bitwise float comparison)."""
    seq = []
    for _ in range(n):
        a = sched.ask(sid)[0]
        loss = float((a["params"]["x"] - 2.0) ** 2)
        sched.tell(sid, a["tid"], loss)
        seq.append((a["tid"], repr(a["params"]["x"])))
    return seq


def _reference(seed, n, n_startup=3):
    ref = StudyScheduler(wal=False)
    sid = ref.create_study(SPACE, seed=seed, n_startup_jobs=n_startup)
    return _drive(ref, sid, n)


# ---------------------------------------------------------------------------
# journal mechanics
# ---------------------------------------------------------------------------


def test_journal_round_trip(tmp_path):
    j = StudyJournal(str(tmp_path / "wal.jsonl"))
    recs = [StudyJournal.admit_rec("s1", SPEC, 7, {"max_trials": 4}),
            StudyJournal.ask_rec("s1", [0, 1], 1234, "tpe"),
            StudyJournal.tell_rec("s1", 0, 0.5, None),
            StudyJournal.close_rec("s1")]
    for r in recs:
        j.append(r)
    j.sync()
    back = list(j.records())
    assert [r["kind"] for r in back] == ["admit", "ask", "tell", "close"]
    assert back[1]["tids"] == [0, 1] and back[1]["seed"] == 1234
    assert back[2]["loss"] == 0.5
    assert j.appends == 4 and j.syncs == 1


def test_journal_torn_final_line(tmp_path):
    """The crash artifact batched fsync allows: a half-written last line
    is skipped by the reader, never fatal."""
    path = str(tmp_path / "wal.jsonl")
    j = StudyJournal(path)
    j.append(StudyJournal.admit_rec("s1", SPEC, 7, {}))
    j.append(StudyJournal.ask_rec("s1", [0], 99, "rand"))
    j.close()
    with open(path, "a") as f:
        f.write('{"kind": "tell", "sid": "s1", "tid": 0, "lo')  # torn
    back = list(StudyJournal(path).records())
    assert [r["kind"] for r in back] == ["admit", "ask"]


def test_journal_rewrite_then_append(tmp_path):
    """Compaction-vs-concurrent-append: an append after rewrite lands in
    the NEW file (the handle reopens), and the reader sees snapshot
    followed by the append."""
    path = str(tmp_path / "wal.jsonl")
    j = StudyJournal(path)
    for i in range(10):
        j.append(StudyJournal.ask_rec("s1", [i], i, "tpe"))
    j.sync()
    j.rewrite([{"kind": "snapshot", "sid": "s1"}])
    j.append(StudyJournal.tell_rec("s1", 3, 1.0, None))
    j.sync()
    kinds = [r["kind"] for r in j.records()]
    assert kinds == ["snapshot", "tell"]
    assert j.compactions == 1


def test_journal_append_failure_is_typed(tmp_path):
    d = tmp_path / "gone"
    j = StudyJournal(str(d / "wal.jsonl"))
    os.rmdir(str(d))  # journal dir vanishes under it
    with pytest.raises(JournalError):
        j.append({"kind": "ask"})


# ---------------------------------------------------------------------------
# crash-resume bitwise pins
# ---------------------------------------------------------------------------


def test_crash_resume_bitwise_wal_only(tmp_path):
    """Without a store the WAL alone regenerates every ask: resumed
    proposals continue bit-identically to an uninterrupted run."""
    ref = _reference(7, 12)
    wal = str(tmp_path / "wal.jsonl")
    s1 = StudyScheduler(wal=wal)
    sid = s1.create_study(SPACE, seed=7, n_startup_jobs=3,
                          space_spec=SPEC, study_id="study-a")
    first = _drive(s1, sid, 7)
    del s1  # crash: no drain, no compaction
    s2 = StudyScheduler(wal=wal)
    assert s2.last_resume["studies"] == 1
    assert s2.last_resume["regenerated"] == 7
    assert s2.last_resume["errors"] == 0
    rest = _drive(s2, sid, 5)
    assert first + rest == ref


def test_crash_resume_bitwise_with_store(tmp_path):
    """With a store the WAL re-admits + realigns the seed stream; docs
    come from disk (nothing regenerated) and a pending (asked, untold)
    trial survives the crash."""
    ref_sched = StudyScheduler(wal=False)
    ref_sid = ref_sched.create_study(SPACE, seed=9, n_startup_jobs=3)
    ref_first = _drive(ref_sched, ref_sid, 6)
    ref_pend = ref_sched.ask(ref_sid)[0]
    ref_sched.tell(ref_sid, ref_pend["tid"], 0.25)
    ref_rest = _drive(ref_sched, ref_sid, 4)

    root = str(tmp_path)
    s1 = StudyScheduler(store_root=root)
    assert s1.journal is not None
    assert s1.journal.path == wal_path_for(root)
    sid = s1.create_study(SPACE, seed=9, n_startup_jobs=3,
                          space_spec=SPEC, study_id=ref_sid)
    first = _drive(s1, sid, 6)
    pend = s1.ask(sid)[0]  # in-flight at the crash
    del s1
    s2 = StudyScheduler(store_root=root)
    st = s2.study_status(sid)
    assert st["n_pending"] == 1 and st["n_trials"] == 7
    assert s2.last_resume["regenerated"] == 0  # store had every doc
    assert (pend["tid"], repr(pend["params"]["x"])) == \
        (ref_pend["tid"], repr(ref_pend["params"]["x"]))
    s2.tell(sid, pend["tid"], 0.25)
    rest = _drive(s2, sid, 4)
    assert first == ref_first and rest == ref_rest


@pytest.mark.parametrize("qname", ("int8", "fp8"))
def test_crash_resume_bitwise_quant_history(tmp_path, monkeypatch, qname):
    """ISSUE 19: the WAL crash-resume pin holds under a QUANTIZED device
    history — values snap to the code grid at ingest, so the journaled
    doc stream already lives on the grid and a resumed scheduler rebuilds
    the same codes: proposals continue bit-identically to an
    uninterrupted same-dtype run."""
    from hyperopt_tpu import quant

    if quant.vals_dtype(qname) is None:
        pytest.skip(f"backend lacks the {qname} storage dtype")
    monkeypatch.setenv("HYPEROPT_TPU_HIST_DTYPE", qname)
    ref = _reference(7, 12)
    wal = str(tmp_path / "wal.jsonl")
    s1 = StudyScheduler(wal=wal)
    sid = s1.create_study(SPACE, seed=7, n_startup_jobs=3,
                          space_spec=SPEC, study_id="study-q-" + qname)
    first = _drive(s1, sid, 7)
    del s1  # crash: no drain, no compaction
    s2 = StudyScheduler(wal=wal)
    assert s2.last_resume["errors"] == 0
    assert s2.last_resume["regenerated"] == 7
    rest = _drive(s2, sid, 5)
    assert first + rest == ref


def test_resume_twice_is_idempotent(tmp_path):
    """Resuming, crashing again immediately and resuming again replays
    to the same state (duplicate tells skipped, nothing double-folds)."""
    ref = _reference(11, 10)
    wal = str(tmp_path / "wal.jsonl")
    s1 = StudyScheduler(wal=wal)
    sid = s1.create_study(SPACE, seed=11, n_startup_jobs=3,
                          space_spec=SPEC, study_id="study-b")
    first = _drive(s1, sid, 6)
    del s1
    s2 = StudyScheduler(wal=wal)  # resume #1, crash untouched
    del s2
    s3 = StudyScheduler(wal=wal)  # resume #2
    assert s3.last_resume["errors"] == 0
    rest = _drive(s3, sid, 4)
    assert first + rest == ref


def test_duplicate_tell_replay_skipped(tmp_path):
    """A tell journaled AND settled into the store before the crash
    replays as a no-op (exactly-once: the posterior never folds it
    twice, n_told stays correct)."""
    root = str(tmp_path)
    s1 = StudyScheduler(store_root=root)
    sid = s1.create_study(SPACE, seed=3, n_startup_jobs=2,
                          space_spec=SPEC)
    _drive(s1, sid, 4)
    # simulate the crash window: duplicate the last tell record in the
    # WAL (journal says it twice, store settled it once)
    with open(s1.journal.path) as f:
        lines = [ln for ln in f.read().splitlines() if ln]
    last_tell = next(ln for ln in reversed(lines)
                     if json.loads(ln)["kind"] == "tell")
    with open(s1.journal.path, "a") as f:
        f.write(last_tell + "\n")
    del s1
    s2 = StudyScheduler(store_root=root)
    assert s2.last_resume["duplicate_tells"] >= 1
    assert s2.last_resume["errors"] == 0
    st = s2.study_status(sid)
    assert st["n_told"] == 4 and st["n_pending"] == 0


def test_resume_with_smaller_max_studies(tmp_path):
    """Journaled studies are grandfathered past a SHRUNKEN admission
    quota (resume must not silently drop state); the quota still blocks
    NEW admissions."""
    root = str(tmp_path)
    s1 = StudyScheduler(store_root=root, max_studies=8)
    sids = [s1.create_study(SPACE, seed=i, n_startup_jobs=2,
                            space_spec=SPEC) for i in range(4)]
    for sid in sids:
        _drive(s1, sid, 3)
    del s1
    s2 = StudyScheduler(store_root=root, max_studies=2)
    assert s2.last_resume["studies"] == 4
    assert {s["study_id"] for s in s2.studies_status()["studies"]} \
        == set(sids)
    with pytest.raises(StudyQuotaError):
        s2.create_study(SPACE, seed=99)
    # the grandfathered studies still serve
    a = s2.ask(sids[0])[0]
    s2.tell(sids[0], a["tid"], 0.1)


def test_compaction_on_settle(tmp_path):
    """A settled (max_trials reached) study compacts the WAL: live
    studies become one snapshot record each, the settled study's
    records drop, and a resume from the compacted WAL continues
    bit-identically."""
    ref = _reference(21, 12)
    root = str(tmp_path)
    s1 = StudyScheduler(store_root=root)
    done_sid = s1.create_study(SPACE, seed=50, n_startup_jobs=2,
                               max_trials=3, space_spec=SPEC)
    live_sid = s1.create_study(SPACE, seed=21, n_startup_jobs=3,
                               space_spec=SPEC, study_id="study-live")
    first = _drive(s1, live_sid, 7)
    _drive(s1, done_sid, 3)  # settles -> compaction
    recs = list(s1.journal.records())
    kinds = {r["kind"] for r in recs}
    assert kinds == {"snapshot"}, kinds
    assert [r["sid"] for r in recs] == [live_sid]
    del s1
    s2 = StudyScheduler(store_root=root)
    rest = _drive(s2, live_sid, 5)
    assert first + rest == ref
    # the settled study's registry entry is forgotten by design
    assert done_sid not in {s["study_id"]
                            for s in s2.studies_status()["studies"]}


def test_void_ask_keeps_streams_aligned(tmp_path, monkeypatch):
    """A failed ask consumed a seed draw; the void WAL record replays
    that draw, so post-resume proposals match the live-continued run."""
    from hyperopt_tpu.service import scheduler as sched_mod

    wal = str(tmp_path / "wal.jsonl")
    s1 = StudyScheduler(wal=wal, degrade=False)
    sid = s1.create_study(SPACE, seed=13, n_startup_jobs=2,
                          space_spec=SPEC, study_id="study-v")
    first = _drive(s1, sid, 4)
    # one ask fails host-side (NOT a device fault: ladder disarmed and
    # the error is a host bug class) -> void record
    orig = sched_mod._Cohort.tick

    def boom(self, *a, **k):
        raise ValueError("host bug")

    monkeypatch.setattr(sched_mod._Cohort, "tick", boom)
    with pytest.raises(ValueError):
        s1.ask(sid)
    monkeypatch.setattr(sched_mod._Cohort, "tick", orig)
    live_rest = _drive(s1, sid, 3)

    s2 = StudyScheduler(wal=wal, degrade=False)
    assert s2.last_resume["errors"] == 0
    # both the live scheduler and the resumed one now continue from the
    # same post-failure state: their NEXT proposals must be identical
    # (same wasted draw, same retired tid, same history)
    live_more = _drive(s1, sid, 3)
    resumed_more = _drive(s2, "study-v", 3)
    assert resumed_more == live_more
    assert first and live_rest  # shape guard: both phases really ran


def test_unresumable_study_is_counted(tmp_path, caplog):
    """A study admitted without a wire spec journals spec=None; replay
    skips it and counts it instead of erroring the whole resume."""
    wal = str(tmp_path / "wal.jsonl")
    s1 = StudyScheduler(wal=wal)
    s1.create_study(SPACE, seed=1, n_startup_jobs=2)  # no space_spec
    sid2 = s1.create_study(SPACE, seed=2, n_startup_jobs=2,
                           space_spec=SPEC)
    del s1
    s2 = StudyScheduler(wal=wal)
    assert s2.last_resume["studies"] == 1
    assert s2.last_resume["skipped"] >= 1
    assert [s["study_id"] for s in s2.studies_status()["studies"]] \
        == [sid2]


def test_wal_disabled_modes(tmp_path, monkeypatch):
    assert StudyScheduler(wal=False).journal is None
    assert StudyScheduler().journal is None  # no store, auto mode
    monkeypatch.setenv("HYPEROPT_TPU_SERVICE_WAL", "off")
    assert StudyScheduler(store_root=str(tmp_path)).journal is None
    monkeypatch.setenv("HYPEROPT_TPU_SERVICE_WAL",
                       str(tmp_path / "explicit.jsonl"))
    s = StudyScheduler()
    assert s.journal is not None
    assert s.journal.path == str(tmp_path / "explicit.jsonl")


# ---------------------------------------------------------------------------
# the real thing: SIGKILL mid-wave in a subprocess, resume in-process
# ---------------------------------------------------------------------------


def test_sigkill_subprocess_resume_bitwise(tmp_path):
    """The acceptance pin: a real process is SIGKILLed inside a cohort
    tick (chaos ``kill@tick``), the parent resumes on the same store
    root, finishes every study's budget, and the complete per-study
    histories are bit-identical to an undisturbed reference."""
    from hyperopt_tpu._env import forced_cpu_env

    n_studies, budget = 3, 8
    root = str(tmp_path / "store")
    env = forced_cpu_env(os.environ)
    env["HYPEROPT_TPU_CHAOS"] = "13:kill@tick:4"
    child = os.path.join(os.path.dirname(__file__), "_service_child.py")
    proc = subprocess.run(
        [sys.executable, child, root, str(n_studies), str(budget)],
        env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == -signal.SIGKILL, (proc.returncode,
                                                proc.stdout, proc.stderr)
    assert "CHILD_FINISHED_WITHOUT_KILL" not in proc.stdout

    # resume on the same root and drive every study to its budget
    sched = StudyScheduler(store_root=root, max_studies=64)
    assert sched.last_resume["studies"] == n_studies
    assert sched.last_resume["errors"] == 0
    for i in range(n_studies):
        sid = f"study-child{i}"
        st = sched._studies[sid]
        # tell any pending (asked-untold) docs first, as the child would
        for d in list(st.trials._dynamic_trials):
            if d["state"] == 0:  # JOB_STATE_NEW
                x = float(d["misc"]["vals"]["x"][0])
                sched.tell(sid, d["tid"], float((x - (i - 1.0)) ** 2))
        while sched.study_status(sid)["n_trials"] < budget:
            a = sched.ask(sid)[0]
            loss = float((a["params"]["x"] - (i - 1.0)) ** 2)
            sched.tell(sid, a["tid"], loss)

    # undisturbed reference, same seeds/order as the child
    ref = StudyScheduler(wal=False, max_studies=64)
    for i in range(n_studies):
        rsid = ref.create_study(SPACE, seed=500 + i, n_startup_jobs=3,
                                study_id=f"study-ref{i}")
        for _ in range(budget):
            a = ref.ask(rsid)[0]
            loss = float((a["params"]["x"] - (i - 1.0)) ** 2)
            ref.tell(rsid, a["tid"], loss)

    for i in range(n_studies):
        mine = sched._studies[f"study-child{i}"].trials
        theirs = ref._studies[f"study-ref{i}"].trials
        got = sorted((d["tid"], repr(float(d["misc"]["vals"]["x"][0])))
                     for d in mine._dynamic_trials)
        want = sorted((d["tid"], repr(float(d["misc"]["vals"]["x"][0])))
                      for d in theirs._dynamic_trials)
        assert got == want, f"study {i} diverged after SIGKILL resume"


def test_land_failure_never_double_journals(tmp_path, monkeypatch):
    """A doc-landing failure AFTER the served-ask record is journaled
    must not also journal a void record: two records would replay the
    one seed draw twice and diverge every later proposal."""
    wal = str(tmp_path / "wal.jsonl")
    s1 = StudyScheduler(wal=wal, degrade=False)
    sid = s1.create_study(SPACE, seed=31, n_startup_jobs=2,
                          space_spec=SPEC, study_id="study-lf")
    first = _drive(s1, sid, 4)

    orig_land = StudyScheduler._land
    fail_once = {"armed": True}

    def flaky_land(self, st, docs):
        if fail_once.pop("armed", False):
            raise OSError("disk full")
        return orig_land(self, st, docs)

    monkeypatch.setattr(StudyScheduler, "_land", flaky_land)
    with pytest.raises(OSError):
        s1.ask(sid)
    live_rest = _drive(s1, sid, 3)

    # exactly ONE ask record per draw for this study (no void shadow
    # behind the journaled-but-unlanded record)
    draws = [r for r in StudyJournal(wal).records()
             if r["kind"] == "ask" and r["sid"] == sid]
    assert len(draws) == 4 + 1 + 3
    assert sum(1 for r in draws if r.get("algo") == "void") == 0

    s2 = StudyScheduler(wal=wal, degrade=False)
    assert s2.last_resume["errors"] == 0
    live_more = _drive(s1, sid, 3)
    resumed_more = _drive(s2, "study-lf", 3)
    assert resumed_more == live_more
    assert first and live_rest
