"""ISSUE 15: the storage-integrity survival plane — CRC32C sealed
records, the ok/torn/corrupt classification table, per-study corruption
quarantine (410, never a boot failure), pre-ISSUE-15 back-compat pinned
bitwise, ENOSPC backpressure, store GC, scrub & repair."""

import errno
import json
import os
import re

import pytest

from hyperopt_tpu import chaos, hp
from hyperopt_tpu.exceptions import StoreFullError
from hyperopt_tpu.service import (QuarantinedStudyError, StudyJournal,
                                  StudyScheduler)
from hyperopt_tpu.service import integrity
from hyperopt_tpu.service.journal import (JournalCorruptError,
                                          JournalError, JournalFullError)
from hyperopt_tpu.service.overload import AdmissionGuard, StoreFullShed

SPACE = {"x": hp.uniform("x", -5, 5)}
SPEC = {"space": {"x": {"dist": "uniform", "args": [-5, 5]}}}


@pytest.fixture(autouse=True)
def _disarm_chaos():
    chaos.configure(None)
    yield
    chaos.reset()


def _flip_digit(line):
    """Deterministically corrupt one line: bump its first digit (keeps
    the JSON framing intact — the checksum must catch it)."""
    return re.sub(r"\d", lambda m: str((int(m.group(0)) + 1) % 10),
                  line, count=1)


def _drive(sched, sid, n):
    seq = []
    for _ in range(n):
        a = sched.ask(sid)[0]
        sched.tell(sid, a["tid"], float((a["params"]["x"] - 1.0) ** 2))
        seq.append((a["tid"], repr(a["params"]["x"])))
    return seq


def _reference(seed, n, n_startup=2):
    ref = StudyScheduler(wal=False)
    sid = ref.create_study(SPACE, seed=seed, n_startup_jobs=n_startup)
    return _drive(ref, sid, n)


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------


def test_crc32c_check_value():
    """The RFC 3720 CRC32C check value — pins the polynomial forever
    (a different poly would silently orphan every sealed record)."""
    assert integrity.crc32c(b"123456789") == 0xE3069283
    assert integrity.crc32c(b"") == 0


def test_seal_verify_round_trip():
    rec = {"kind": "ask", "sid": "s1", "tids": [0, 1], "seed": 123,
           "loss": 0.125, "ts": 1722800000.25}
    line = integrity.seal(rec)
    parsed = json.loads(line)
    assert integrity.verify_obj(parsed) == integrity.OK
    assert parsed == rec  # the checksum field was popped


def test_seal_refuses_double_seal():
    with pytest.raises(ValueError):
        integrity.seal({"kind": "x", "c": "deadbeef"})


def test_classification_table(tmp_path):
    """The satellite's table: bit-flip, truncated mid-file line,
    truncated final record, duplicate line, empty file, pre-ISSUE-15
    unchecksummed file."""
    recs = [{"kind": "admit", "sid": f"s{i}", "seed": i}
            for i in range(6)]
    sealed = [integrity.seal(r) for r in recs]

    # bit-flip mid-file -> corrupt; duplicate line -> ok twice;
    # truncated mid-file line -> corrupt; truncated final record -> torn
    path = str(tmp_path / "table.jsonl")
    with open(path, "w") as f:
        f.write(sealed[0] + "\n")
        f.write(_flip_digit(sealed[1]) + "\n")
        f.write(sealed[2] + "\n")
        f.write(sealed[2] + "\n")          # duplicate line
        f.write(sealed[3][:25] + "\n")     # truncated mid-file
        f.write(sealed[4] + "\n")
        f.write(sealed[5][:-9])            # truncated record boundary
    got = [(c.status, c.lineno) for c in integrity.iter_checked_jsonl(path)]
    assert got == [(integrity.OK, 1), (integrity.CORRUPT, 2),
                   (integrity.OK, 3), (integrity.OK, 4),
                   (integrity.CORRUPT, 5), (integrity.OK, 6),
                   (integrity.TORN, 7)]

    # empty file
    empty = str(tmp_path / "empty.jsonl")
    open(empty, "w").close()
    assert list(integrity.iter_checked_jsonl(empty)) == []

    # pre-ISSUE-15 unchecksummed file: every line classifies unchecked
    # and parses to the record verbatim
    old = str(tmp_path / "old.jsonl")
    with open(old, "w") as f:
        for r in recs[:3]:
            f.write(json.dumps(r, sort_keys=True,
                               separators=(",", ":")) + "\n")
    got = list(integrity.iter_checked_jsonl(old))
    assert [c.status for c in got] == [integrity.UNCHECKED] * 3
    assert [c.rec for c in got] == recs[:3]


def test_salvage_sid():
    line = integrity.seal({"kind": "tell", "sid": "study-abc", "tid": 3})
    assert integrity.salvage_sid(line[: len(line) // 1]) == "study-abc"
    assert integrity.salvage_sid('{"kind":"tell","ti') is None


def test_is_enospc():
    assert integrity.is_enospc(OSError(errno.ENOSPC, "full"))
    assert not integrity.is_enospc(OSError(errno.EIO, "io"))
    assert not integrity.is_enospc(ValueError("x"))


def test_disk_watermark_thresholds():
    class _SV:
        f_frsize = 4096
        f_blocks = 1000
        f_bavail = 10  # 1% free, 40960 bytes

    wm = integrity.DiskWatermark("/", threshold=0.02, poll_sec=0.0,
                                 statvfs=lambda _p: _SV())
    s = wm.sample(force=True)
    assert s["low"] and s["free_bytes"] == 40960
    wm_bytes = integrity.DiskWatermark("/", threshold=50000, poll_sec=0.0,
                                       statvfs=lambda _p: _SV())
    assert wm_bytes.sample(force=True)["low"]
    wm_off = integrity.DiskWatermark("/", threshold=None, poll_sec=0.0,
                                     statvfs=lambda _p: _SV())
    assert not wm_off.sample(force=True)["low"]


# ---------------------------------------------------------------------------
# journal: typed ENOSPC, verified compaction
# ---------------------------------------------------------------------------


def test_journal_enospc_is_typed_and_retryable(tmp_path):
    j = StudyJournal(str(tmp_path / "wal.jsonl"))
    chaos.configure("7:enospc@wal:1.0")
    with pytest.raises(JournalFullError) as ei:
        j.append({"kind": "ask", "sid": "s1"})
    assert isinstance(ei.value, StoreFullError)
    assert isinstance(ei.value, JournalError)
    chaos.configure(None)
    j.append({"kind": "ask", "sid": "s1"})  # recovers
    j.sync()


def test_rewrite_refuses_to_launder_corruption(tmp_path):
    """Compaction aborts (keeping the old chain) when the records it
    would discard fail verification — the satellite's laundering
    window."""
    path = str(tmp_path / "wal.jsonl")
    j = StudyJournal(path)
    for i in range(4):
        j.append({"kind": "ask", "sid": "s1", "seed": i})
    j.close()
    lines = open(path).read().splitlines()
    lines[1] = _flip_digit(lines[1])
    open(path, "w").write("\n".join(lines) + "\n")
    before = open(path).read()
    with pytest.raises(JournalCorruptError):
        j.rewrite([{"kind": "snapshot", "sid": "s1"}])
    assert open(path).read() == before  # old chain intact


def test_atomic_write_enospc_typed(tmp_path, monkeypatch):
    from hyperopt_tpu import filestore

    def bomb(_path, _payload):
        raise OSError(errno.ENOSPC, "disk full")

    monkeypatch.setattr(os, "replace",
                        lambda *a: (_ for _ in ()).throw(
                            OSError(errno.ENOSPC, "full")))
    with pytest.raises(StoreFullError):
        filestore._atomic_write(str(tmp_path / "f"), b"x")
    _ = bomb


# ---------------------------------------------------------------------------
# quarantine: per-study fault, never a process fault
# ---------------------------------------------------------------------------


def test_corrupt_record_quarantines_study_not_process(tmp_path):
    """The tentpole pin: one corrupt mid-file record quarantines ITS
    study (410), every untouched study resumes bit-identically, the
    segment is renamed aside with a reason record, and a second resume
    is idempotent."""
    ref = _reference(7, 9)
    wal = str(tmp_path / "wal.jsonl")
    s1 = StudyScheduler(wal=wal)
    sa = s1.create_study(SPACE, seed=7, n_startup_jobs=2,
                         space_spec=SPEC, study_id="study-a")
    sb = s1.create_study(SPACE, seed=11, n_startup_jobs=2,
                         space_spec=SPEC, study_id="study-b")
    first = _drive(s1, sa, 5)
    _drive(s1, sb, 5)
    del s1

    lines = open(wal).read().splitlines()
    idx = max(i for i, l in enumerate(lines)
              if '"sid":"study-b"' in l and i < len(lines) - 2)
    lines[idx] = _flip_digit(lines[idx])
    open(wal, "w").write("\n".join(lines) + "\n")

    s2 = StudyScheduler(wal=wal)
    assert s2.last_resume["corrupt_records"] == 1
    assert s2.last_resume["quarantined"] == 1
    assert s2.last_resume["errors"] == 0
    with pytest.raises(QuarantinedStudyError):
        s2.ask("study-b")
    with pytest.raises(QuarantinedStudyError):
        s2.tell("study-b", 0, 0.5)
    # the evidence segment, with its sealed reason record
    qpath = wal + ".quarantined"
    assert os.path.exists(qpath)
    tail = list(integrity.iter_checked_jsonl(qpath))[-1]
    assert tail.rec["kind"] == "quarantine_reason"
    assert tail.status == integrity.OK
    # untouched study: bitwise continuation
    assert first + _drive(s2, sa, 4) == ref
    # /studies lists the quarantined study
    table = s2.studies_status()
    states = {s["study_id"]: s["state"] for s in table["studies"]}
    assert states["study-b"] == "quarantined"
    assert "study-b" in table["quarantined"]
    # timeline carries the quarantine event
    tl = s2.study_timeline("study-b")
    assert any(ev["event"] == "quarantine" for ev in tl["events"])
    # resume twice with the quarantined segment present: idempotent
    del s2
    s3 = StudyScheduler(wal=wal)
    assert "study-b" in s3._quarantined
    with pytest.raises(QuarantinedStudyError):
        s3.ask("study-b")
    states = {s["study_id"]: s["state"]
              for s in s3.studies_status()["studies"]}
    assert states == {"study-a": "active", "study-b": "quarantined"}


def test_quarantined_http_semantics(tmp_path):
    """410 + quarantined flag over the REAL handler path, /studies
    flag, timeline event — the satellite's HTTP table."""
    from hyperopt_tpu.service.server import ServiceHTTPServer

    root = str(tmp_path)
    s1 = StudyScheduler(store_root=root)
    sid = s1.create_study(SPACE, seed=3, n_startup_jobs=2,
                          space_spec=SPEC, study_id="study-q")
    _drive(s1, sid, 4)
    del s1
    wal = os.path.join(root, "service.wal.jsonl")
    lines = open(wal).read().splitlines()
    idx = max(i for i, l in enumerate(lines) if '"sid":"study-q"' in l
              and i < len(lines) - 1)
    lines[idx] = _flip_digit(lines[idx])
    open(wal, "w").write("\n".join(lines) + "\n")

    server = ServiceHTTPServer(0, scheduler=StudyScheduler(
        store_root=root))
    code, payload = server.handle("POST", "/ask", {"study_id": "study-q"})
    assert code == 410 and payload["quarantined"] is True
    code, payload = server.handle("POST", "/tell",
                                  {"study_id": "study-q", "tid": 0,
                                   "loss": 0.1})
    assert code == 410
    code, table = server.handle("GET", "/studies", {})
    assert code == 200
    entry = next(s for s in table["studies"]
                 if s["study_id"] == "study-q")
    assert entry["state"] == "quarantined"
    code, tl = server.handle("GET", "/study/study-q/timeline", {})
    assert code == 200
    assert any(ev["event"] == "quarantine" for ev in tl["events"])


def test_corrupt_tail_tell_reconciles_from_store(tmp_path):
    """A bit-flip on the FINAL WAL line (an acknowledged tell) is
    indistinguishable from a torn tail — but the doc already settled
    DONE in the store, so resume reconciles the counter instead of
    reporting a phantom pending ask; the study stays healthy and its
    stream bitwise (smoke-found regression)."""
    ref = _reference(17, 8)
    root = str(tmp_path)
    s1 = StudyScheduler(store_root=root)
    sid = s1.create_study(SPACE, seed=17, n_startup_jobs=2,
                          space_spec=SPEC, study_id="study-t")
    first = _drive(s1, sid, 5)
    del s1
    wal = os.path.join(root, "service.wal.jsonl")
    lines = open(wal).read().splitlines()
    assert '"kind":"tell"' in lines[-1]
    lines[-1] = lines[-1][:-10]  # destroy the final (tell) record
    open(wal, "w").write("\n".join(lines) + "\n")
    s2 = StudyScheduler(store_root=root)
    assert s2.last_resume["reconciled_tells"] == 1
    assert s2.last_resume["quarantined"] == 0
    st = s2.study_status(sid)
    assert st["state"] == "active" and st["n_pending"] == 0
    assert first + _drive(s2, sid, 3) == ref


def test_pre_issue15_wal_resumes_bitwise(tmp_path):
    """Back-compat acceptance pin: an UNCHECKSUMMED (pre-ISSUE-15) WAL
    resumes bit-identically on the new code path."""
    ref = _reference(21, 10)
    wal = str(tmp_path / "wal.jsonl")
    s1 = StudyScheduler(wal=wal)
    s1.journal.checksum = False  # write the old format
    sid = s1.create_study(SPACE, seed=21, n_startup_jobs=2,
                          space_spec=SPEC, study_id="study-old")
    first = _drive(s1, sid, 6)
    del s1
    # no record carries the checksum field
    for rec in list(StudyJournal(wal).records()):
        assert "c" not in rec
    s2 = StudyScheduler(wal=wal)  # new code path, checksums armed
    assert s2.last_resume["unchecked"] > 0
    assert s2.last_resume["verified"] == 0
    assert s2.last_resume["corrupt_records"] == 0
    assert first + _drive(s2, sid, 4) == ref


def test_fleet_adoption_corrupt_middle_epoch(tmp_path):
    """The satellite's chain case: adoption of an epoch chain whose
    MIDDLE epoch holds a corrupt record quarantines that study and
    adopts every other bit-identically (a per-study fault — the shard
    still serves)."""
    from hyperopt_tpu.service.fleet import FleetReplica

    root = str(tmp_path)
    wal_dir = os.path.join(root, "fleet", "wal", "shard0000")
    os.makedirs(wal_dir)
    e1 = os.path.join(wal_dir, "e00001.seed.jsonl")
    e2 = os.path.join(wal_dir, "e00002.seed.jsonl")

    ref = _reference(31, 8)
    s1 = StudyScheduler(store_root=root, wal=e1)
    sa = s1.create_study(SPACE, seed=31, n_startup_jobs=2,
                         space_spec=SPEC, study_id="study-a")
    sb = s1.create_study(SPACE, seed=37, n_startup_jobs=2,
                         space_spec=SPEC, study_id="study-b")
    first = _drive(s1, sa, 3)
    _drive(s1, sb, 3)
    del s1
    s2 = StudyScheduler(store_root=root, wal=e2, auto_resume=False)
    s2.resume(StudyJournal(e1))
    first += _drive(s2, sa, 2)
    _drive(s2, sb, 2)
    del s2
    # corrupt one study-b record in the MIDDLE epoch (e2 is the newest
    # of the seed chain; the adopter's own epoch comes after it)
    lines = open(e2).read().splitlines()
    idx = max(i for i, l in enumerate(lines) if '"sid":"study-b"' in l
              and i < len(lines) - 1)
    lines[idx] = _flip_digit(lines[idx])
    open(e2, "w").write("\n".join(lines) + "\n")

    replica = FleetReplica(root, n_shards=1, replica_id="r1",
                           lease_ttl=30.0,
                           scheduler_kwargs={"max_studies": 64})
    assert replica.adopt(0) is True
    sched = replica.schedulers[0]
    assert "study-b" in sched._quarantined
    with pytest.raises(QuarantinedStudyError):
        sched.ask("study-b")
    # the corrupt epoch file was preserved as evidence
    assert any(f.startswith("e00002") and ".quarantined" in f
               for f in os.listdir(wal_dir))
    # the healthy study adopted bit-identically and keeps proposing
    assert first + _drive(sched, sa, 3) == ref
    # quarantine survives the adopter's own compacted epoch
    kinds = {r["kind"] for r in sched.journal.records()}
    assert "quarantine" in kinds


# ---------------------------------------------------------------------------
# ENOSPC backpressure + store hygiene
# ---------------------------------------------------------------------------


def test_store_full_latch_sheds_and_reprobes():
    t = [0.0]
    guard = AdmissionGuard(max_queue=4, clock=lambda: t[0])
    guard.set_store_full(True, reason="disk full", retry_after=1.0)
    with pytest.raises(StoreFullShed) as ei:
        guard.admit_ask()
    assert ei.value.retry_after == 1.0
    # tells are NOT shed by the store-full latch (shed last)
    assert guard.admit_tell() == "tell"
    guard.release("tell")
    # latch expires -> the next ask is the probe
    t[0] = 2.1
    assert guard.admit_ask() == "ask"
    guard.release("ask")


def test_enospc_latch_survives_healthy_watermark(tmp_path):
    """Review pin: an ENOSPC-armed latch must NOT clear just because
    statvfs shows free blocks (EDQUOT, failing controller) — only a
    successful durable write clears it; and a WATERMARK-armed latch
    keeps the guard re-armed while space stays low (the guard window
    would otherwise expire after ~2s of shedding)."""
    root = str(tmp_path)
    sched = StudyScheduler(store_root=root)
    guard = AdmissionGuard(max_queue=4, metrics=sched.metrics)
    sched.overload = guard
    sid = sched.create_study(SPACE, seed=5, n_startup_jobs=2,
                             space_spec=SPEC)
    a = sched.ask(sid)[0]
    chaos.configure("7:enospc@wal:1.0")
    with pytest.raises(StoreFullError):
        sched.tell(sid, a["tid"], 0.5)
    assert sched._store_full and sched._store_full_src == "enospc"
    # a watermark poll showing plenty of space must NOT clear it
    sched._check_store(force=True)
    assert sched._store_full
    # ...but a successful durable write must
    chaos.configure(None)
    sched.tell(sid, a["tid"], 0.5)
    assert not sched._store_full

    # watermark-armed: the guard latch re-arms on every low poll
    t = [0.0]
    guard2 = AdmissionGuard(max_queue=4, clock=lambda: t[0])
    sched.overload = guard2
    sched.watermark = integrity.DiskWatermark(
        root, threshold=0.999999, poll_sec=0.0)  # everything is "low"
    sched._check_store(force=True)
    assert sched._store_full_src == "watermark"
    t[0] = 10.0  # past the guard window: would have expired...
    sched._check_store(force=True)  # ...but the low poll re-arms it
    with pytest.raises(StoreFullShed):
        guard2.admit_ask()
    # space returns: the watermark latch clears on the poll
    sched.watermark = integrity.DiskWatermark(root, threshold=None,
                                              poll_sec=0.0)
    sched._check_store(force=True)
    assert not sched._store_full


def test_enospc_on_tell_is_507_typed_and_recovers(tmp_path):
    """ENOSPC at the tell's durability point: typed StoreFullError out
    (507), nothing applied, and the SAME tell lands once space frees —
    tells shed last, never lost."""
    root = str(tmp_path)
    sched = StudyScheduler(store_root=root)
    sid = sched.create_study(SPACE, seed=5, n_startup_jobs=2,
                             space_spec=SPEC)
    a = sched.ask(sid)[0]
    chaos.configure("7:enospc@wal:1.0")
    with pytest.raises(StoreFullError):
        sched.tell(sid, a["tid"], 0.5)
    st = sched.study_status(sid)
    assert st["n_told"] == 0  # write-ahead: nothing applied
    chaos.configure(None)
    sched.tell(sid, a["tid"], 0.5)  # the retry lands
    assert sched.study_status(sid)["n_told"] == 1


def test_filestore_gc_reclaims_garbage(tmp_path):
    import pickle
    import time as _time

    from hyperopt_tpu.filestore import FileStore

    store = FileStore(str(tmp_path / "st"))
    doc = {"tid": 1, "state": 2, "result": {"loss": 0.5},
           "misc": {}, "owner": None, "book_time": None,
           "refresh_time": None}
    store.write_doc(doc)  # done/1.pkl
    # superseded new/ copy beside the terminal doc
    with open(os.path.join(store.root, "new", "1.pkl"), "wb") as f:
        f.write(pickle.dumps(dict(doc, state=0)))
    # stale tmp + expired flight dump + fresh tmp (must survive)
    old = _time.time() - 3600
    stale = os.path.join(store.root, "done", "1.pkl.tmp.9.9")
    open(stale, "wb").write(b"\0" * 64)
    os.utime(stale, (old, old))
    fresh = os.path.join(store.root, "done", "2.pkl.tmp.8.8")
    open(fresh, "wb").write(b"\0" * 64)
    dump = store.flight_dump_path("host:1")
    open(dump, "w").write('{"kind":"x"}\n')
    os.utime(dump, (old - 8 * 86400, old - 8 * 86400))
    q = os.path.join(store.root, "done", "9.pkl.quarantined")
    open(q, "wb").write(b"evidence")

    stats = store.gc(tmp_max_age=60.0, flight_max_age=7 * 86400.0)
    assert stats["removed"] == 3
    assert stats["reclaimed_bytes"] > 0
    assert not os.path.exists(os.path.join(store.root, "new", "1.pkl"))
    assert not os.path.exists(stale)
    assert not os.path.exists(dump)
    assert os.path.exists(fresh)      # live writer's tmp untouched
    assert os.path.exists(q)          # evidence never collected
    assert os.path.exists(store._path(2, 1))  # the real doc stays


def test_gc_store_root_removes_compacted_ancestor_epochs(tmp_path):
    root = str(tmp_path)
    d = os.path.join(root, "fleet", "wal", "shard0000")
    os.makedirs(d)
    j1 = StudyJournal(os.path.join(d, "e00001.r0.jsonl"))
    j1.append({"kind": "admit", "sid": "s1", "seed": 1})
    j1.close()
    j2 = StudyJournal(os.path.join(d, "e00002.r1.jsonl"))
    j2.append({"kind": "snapshot", "sid": "s1", "seed": 1})
    j2.close()
    stats = integrity.gc_store_root(root)
    assert stats["removed"] == 1
    assert not os.path.exists(j1.path)   # ancestor redundant: removed
    assert os.path.exists(j2.path)       # snapshot-led head stays


def test_census_write_failure_under_disk_full(monkeypatch, caplog):
    """The satellite: census appends degrade to warn-once on ENOSPC —
    never an exception, never a second warning."""
    import logging

    from hyperopt_tpu.service.compile_plane import SignatureCensus

    census = SignatureCensus("/tmp/does-not-matter-census.jsonl")
    real_open = os.open

    def full_open(path, flags, mode=0o777):
        if "census" in str(path):
            raise OSError(errno.ENOSPC, "disk full")
        return real_open(path, flags, mode)

    monkeypatch.setattr(os, "open", full_open)
    spec = {"space": {"x": {"dist": "uniform", "args": [0, 1]}}}
    with caplog.at_level(logging.WARNING):
        for _ in range(9):  # crosses the 1 and 8 milestones
            census.note(spec, {"gamma": 0.25}, 16, 1, 1)
    warnings = [r for r in caplog.records
                if "census" in r.getMessage()]
    assert len(warnings) == 1  # warn-once
    assert census._counts  # counting continues in-process


def test_scrub_detects_and_repairs(tmp_path):
    from hyperopt_tpu.service import scrub

    root = str(tmp_path)
    s1 = StudyScheduler(store_root=root)
    sa = s1.create_study(SPACE, seed=41, n_startup_jobs=2,
                         space_spec=SPEC, study_id="study-a")
    sb = s1.create_study(SPACE, seed=43, n_startup_jobs=2,
                         space_spec=SPEC, study_id="study-b")
    _drive(s1, sa, 3)
    _drive(s1, sb, 3)
    del s1
    wal = os.path.join(root, "service.wal.jsonl")
    lines = open(wal).read().splitlines()
    idx = max(i for i, l in enumerate(lines) if '"sid":"study-b"' in l
              and i < len(lines) - 1)
    lines[idx] = _flip_digit(lines[idx])
    open(wal, "w").write("\n".join(lines) + "\n")

    report = scrub.scan_store(root)
    assert not report["clean"]
    assert any(f["kind"] == "wal_corrupt" and f["sid"] == "study-b"
               for f in report["faults"])
    actions = scrub.repair_store(root, report)
    assert any(a["action"] == "quarantine_segment" for a in actions)
    post = scrub.scan_store(root)
    assert post["clean"]
    # the repaired store boots: healthy active, corrupt quarantined
    s2 = StudyScheduler(store_root=root)
    states = {s["study_id"]: s["state"]
              for s in s2.studies_status()["studies"]}
    assert states["study-a"] == "active"
    assert states["study-b"] == "quarantined"
