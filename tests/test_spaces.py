"""Space IR + sampler tests.

Modeled on the reference's DSL/stochastic-node tests
(``hyperopt/pyll/tests/test_base.py``, ``test_stochastic.py``,
``tests/test_pyll_utils.py`` — SURVEY.md §4): statistical assertions on
bounds, quantization and moments; conditional-space config extraction;
DuplicateLabel behavior.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hyperopt_tpu import hp, spaces
from hyperopt_tpu.exceptions import DuplicateLabel, InvalidAnnotatedParameter
from hyperopt_tpu.spaces import compile_space, expr_to_config, space_eval

N = 4000


def batch_draw(space, n=N, seed=0):
    cs = compile_space(space)
    keys = jax.random.split(jax.random.PRNGKey(seed), n)
    flat = jax.jit(jax.vmap(cs.sample_flat))(keys)
    return cs, {k: np.asarray(v) for k, v in flat.items()}


def test_uniform_bounds_and_moments():
    _, flat = batch_draw(hp.uniform("x", -3.0, 7.0))
    x = flat["x"]
    assert x.min() >= -3.0 and x.max() <= 7.0
    assert abs(x.mean() - 2.0) < 0.15
    assert abs(x.std() - 10.0 / np.sqrt(12)) < 0.15


def test_quniform_multiples():
    _, flat = batch_draw(hp.quniform("x", 0.0, 10.0, 0.5))
    x = flat["x"]
    assert np.allclose(np.round(x / 0.5) * 0.5, x, atol=1e-5)


def test_loguniform_log_bounds():
    _, flat = batch_draw(hp.loguniform("x", np.log(1e-3), np.log(1e3)))
    x = flat["x"]
    assert x.min() >= 1e-3 - 1e-9 and x.max() <= 1e3 + 1e-3
    lx = np.log(x)
    assert abs(lx.mean()) < 0.3  # symmetric in log space


def test_normal_moments():
    _, flat = batch_draw(hp.normal("x", 5.0, 2.0))
    x = flat["x"]
    assert abs(x.mean() - 5.0) < 0.15
    assert abs(x.std() - 2.0) < 0.15


def test_lognormal_is_exp_normal():
    _, flat = batch_draw(hp.lognormal("x", 1.0, 0.5))
    lx = np.log(flat["x"])
    assert abs(lx.mean() - 1.0) < 0.05
    assert abs(lx.std() - 0.5) < 0.05


def test_qlognormal_quantized_nonneg():
    _, flat = batch_draw(hp.qlognormal("x", 0.0, 1.0, 2.0))
    x = flat["x"]
    assert np.allclose(np.round(x / 2.0) * 2.0, x, atol=1e-4)
    assert x.min() >= 0.0


def test_randint_range():
    _, flat = batch_draw(hp.randint("i", 7))
    i = flat["i"]
    assert i.dtype.kind == "i"
    assert i.min() >= 0 and i.max() <= 6
    counts = np.bincount(i, minlength=7)
    assert (counts > N / 7 * 0.7).all()


def test_randint_low_high():
    _, flat = batch_draw(hp.randint("i", 3, 9))
    i = flat["i"]
    assert i.min() >= 3 and i.max() <= 8


def test_uniformint_inclusive():
    _, flat = batch_draw(hp.uniformint("i", 1, 4))
    i = flat["i"]
    assert set(np.unique(i)) == {1, 2, 3, 4}


def test_pchoice_frequencies():
    space = hp.pchoice("c", [(0.1, "a"), (0.2, "b"), (0.7, "c")])
    _, flat = batch_draw(space)
    freq = np.bincount(flat["c"], minlength=3) / N
    assert np.allclose(freq, [0.1, 0.2, 0.7], atol=0.03)


def test_pchoice_bad_probs():
    with pytest.raises(InvalidAnnotatedParameter):
        hp.pchoice("c", [(0.5, "a"), (0.2, "b")])


def test_choice_conditions_and_active():
    space = {
        "kind": hp.choice(
            "kind",
            [
                {"name": "svm", "C": hp.loguniform("C", -5, 5)},
                {"name": "rf", "depth": hp.randint("depth", 10)},
            ],
        )
    }
    cs = compile_space(space)
    assert cs.params["C"].conditions == (("kind", 0),)
    assert cs.params["depth"].conditions == (("kind", 1),)
    assert cs.params["kind"].conditions == ()

    flat = {k: np.asarray(v) for k, v in cs.sample_flat_jit(jax.random.PRNGKey(3)).items()}
    act = cs.active_flat({k: v.item() for k, v in flat.items()})
    k = flat["kind"].item()
    assert act["C"] == (k == 0)
    assert act["depth"] == (k == 1)

    structured = cs.assemble({k: v.item() for k, v in flat.items()})
    assert structured["kind"]["name"] == ("svm" if k == 0 else "rf")


def test_duplicate_label_raises():
    with pytest.raises(DuplicateLabel):
        compile_space([hp.uniform("x", 0, 1), hp.normal("x", 0, 1)])


def test_arithmetic_on_params():
    space = hp.uniform("x", 0.0, 1.0) * 10 + 5
    cs = compile_space(space)
    flat = cs.sample_flat_jit(jax.random.PRNGKey(0))
    v = cs.assemble({"x": np.asarray(flat["x"]).item()})
    assert 5.0 <= v <= 15.0


def test_space_eval_parity():
    space = {
        "lr": hp.loguniform("lr", -5, 0),
        "arch": hp.choice("arch", [("mlp", hp.randint("width", 8)), ("cnn",)]),
    }
    out = space_eval(space, {"lr": [0.01], "arch": [0], "width": [3]})
    assert out["lr"] == 0.01
    assert out["arch"] == ("mlp", 3)
    out2 = space_eval(space, {"lr": 0.5, "arch": 1})
    assert out2["arch"] == ("cnn",)


def test_expr_to_config():
    space = hp.choice("c", [hp.uniform("a", 0, 1), hp.uniform("b", 0, 1)])
    cfg = expr_to_config(space)
    assert set(cfg) == {"c", "a", "b"}
    assert cfg["a"]["conditions"] == (("c", 0),)
    assert cfg["c"]["dist"].family == "randint"


def test_sample_structured():
    space = {"x": hp.uniform("x", 0, 1), "c": hp.choice("c", [1, 2])}
    out = spaces.sample(space, 0)
    assert 0 <= out["x"] <= 1
    assert out["c"] in (1, 2)


def test_traced_assemble_switch():
    space = {"y": hp.choice("c", [hp.uniform("a", 0.0, 1.0) + 1.0, hp.uniform("b", 0.0, 1.0) + 3.0])}
    cs = compile_space(space)

    def f(key):
        flat = cs.sample_flat(key)
        return cs.assemble(flat, traced=True)["y"]

    ys = np.asarray(jax.vmap(f)(jax.random.split(jax.random.PRNGKey(0), 512)))
    assert (((1.0 <= ys) & (ys <= 2.0)) | ((3.0 <= ys) & (ys <= 4.0))).all()
    assert ((1.0 <= ys) & (ys <= 2.0)).any() and ((3.0 <= ys) & (ys <= 4.0)).any()


def test_sample_flat_deterministic():
    cs = compile_space(hp.uniform("x", 0, 1))
    a = cs.sample_flat_jit(jax.random.PRNGKey(42))["x"]
    b = cs.sample_flat_jit(jax.random.PRNGKey(42))["x"]
    assert jnp.array_equal(a, b)


def test_assemble_traced_union_merges_different_branch_structures():
    # traced choice assembly must union-merge dict branches with different
    # keys: the selected branch's values appear, the other branch's slots
    # read as typed zeros, equal string leaves pass through, unequal ones
    # are omitted (they cannot participate in traced compute)
    import jax
    import jax.numpy as jnp

    from hyperopt_tpu import hp
    from hyperopt_tpu.spaces import compile_space

    space = hp.choice("arch", [
        {"kind": "mlp", "tag": "same", "w": hp.quniform("w", 16, 256, 16)},
        {"kind": "attn", "tag": "same", "h": hp.randint("h", 1, 9)},
    ])
    cs = compile_space(space)

    def probe(flat):
        d = cs.assemble(flat, traced=True)
        assert "kind" not in d  # differing strings are omitted
        assert d["tag"] == "same"  # equal strings pass through
        return d["w"] + 10.0 * d["h"]

    out0 = jax.jit(probe)({"arch": jnp.int32(0), "w": jnp.float32(32.0),
                           "h": jnp.int32(5)})
    out1 = jax.jit(probe)({"arch": jnp.int32(1), "w": jnp.float32(32.0),
                           "h": jnp.int32(5)})
    assert float(out0) == 32.0  # branch 0: w live, h reads as 0
    assert float(out1) == 50.0  # branch 1: h live, w reads as 0


def test_assemble_traced_rejects_unequal_sequence_lengths():
    import jax
    import jax.numpy as jnp
    import pytest as _pytest

    from hyperopt_tpu import hp
    from hyperopt_tpu.exceptions import InvalidAnnotatedParameter
    from hyperopt_tpu.spaces import compile_space

    space = hp.choice("fam", [
        {"xs": [hp.uniform("a0", 0, 1), hp.uniform("a1", 0, 1)]},
        {"xs": [hp.uniform("b0", 0, 1)]},
    ])
    cs = compile_space(space)
    flat = {"fam": jnp.int32(0), "a0": jnp.float32(0.5),
            "a1": jnp.float32(0.5), "b0": jnp.float32(0.5)}
    with _pytest.raises(InvalidAnnotatedParameter, match="different lengths"):
        jax.jit(lambda f: cs.assemble(f, traced=True)["xs"][0])(flat)


def test_pyll_stochastic_sample_compat():
    # the reference's canonical space-preview idiom works unchanged:
    # hyperopt.pyll.stochastic.sample(space[, rng]) -> structured draw
    import numpy as np
    import pytest as _pytest

    from hyperopt_tpu import hp, pyll

    space = {
        "lr": hp.loguniform("lr", -6, 0),
        "arch": hp.choice("arch", ["a", "b"]),
    }
    s1 = pyll.stochastic.sample(space, np.random.default_rng(0))
    s2 = pyll.stochastic.sample(space, np.random.RandomState(0))
    s3 = pyll.stochastic.sample(space, 42)
    s4 = pyll.stochastic.sample(space)  # fresh entropy
    for s in (s1, s2, s3, s4):
        assert np.exp(-6) <= s["lr"] <= 1.0
        assert s["arch"] in ("a", "b")
    # same int seed -> same draw (deterministic path)
    assert pyll.stochastic.sample(space, 42) == s3
    # interpreter internals give a guidance error, not an import crash
    with _pytest.raises(AttributeError, match="compiled space IR"):
        pyll.scope
    # as_apply aliases the IR builder
    assert pyll.as_apply(space) is not None


def test_assemble_traced_string_choice_raises_with_guidance():
    import jax
    import jax.numpy as jnp
    import pytest as _pytest

    from hyperopt_tpu import hp
    from hyperopt_tpu.exceptions import InvalidAnnotatedParameter
    from hyperopt_tpu.spaces import compile_space

    cs = compile_space({"act": hp.choice("act", ["relu", "tanh"])})
    with _pytest.raises(InvalidAnnotatedParameter, match="encode the options"):
        jax.jit(lambda f: cs.assemble(f, traced=True))({"act": jnp.int32(0)})
    # mixed container/leaf branches are a space bug, reported at the slot
    cs2 = compile_space(hp.choice("opt", [
        {"inner": {"lr": hp.uniform("lr", 0, 1)}},
        {"inner": 0.5},
    ]))
    flat = {"opt": jnp.int32(0), "lr": jnp.float32(0.3)}
    with _pytest.raises(InvalidAnnotatedParameter, match="mix containers"):
        jax.jit(lambda f: cs2.assemble(f, traced=True))(flat)


def test_grouped_sampler_bitwise_matches_unrolled():
    # sample_flat batches same-family labels through draw_dist_group; every
    # per-label draw must equal the unrolled draw_dist call bitwise (same
    # fold_in keys, same formulas) — eager AND under jit+vmap (the rand
    # suggest kernel's shape)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from hyperopt_tpu import hp
    from hyperopt_tpu.spaces import compile_space, draw_dist, label_hash

    space = {
        "u1": hp.uniform("u1", -5, 5), "u2": hp.uniform("u2", 0, 1),
        "qu1": hp.quniform("qu1", 0, 10, 2), "qu2": hp.quniform("qu2", -4, 4, 0.5),
        "lu1": hp.loguniform("lu1", -3, 2), "lu2": hp.loguniform("lu2", 0, 1),
        "qlu1": hp.qloguniform("qlu1", 0, 3, 1), "qlu2": hp.qloguniform("qlu2", 1, 4, 2),
        "n1": hp.normal("n1", 0, 1), "n2": hp.normal("n2", 3, 0.5),
        "qn1": hp.qnormal("qn1", 0, 2, 1), "qn2": hp.qnormal("qn2", 5, 1, 0.5),
        "ln1": hp.lognormal("ln1", 0, 1), "ln2": hp.lognormal("ln2", 1, 0.25),
        "qln1": hp.qlognormal("qln1", 0, 1, 1), "qln2": hp.qlognormal("qln2", 1, 1, 2),
        "ri1": hp.randint("ri1", 0, 7), "ri2": hp.randint("ri2", 3, 20),
        "ui1": hp.uniformint("ui1", 1, 9), "ui2": hp.uniformint("ui2", 0, 3),
        "c1": hp.choice("c1", ["a", "b", "c"]), "c2": hp.choice("c2", [1, 2, 3]),
        "c4": hp.choice("c4", [1, 2, 3, 4]),  # different K: its own group
    }
    cs = compile_space(space)
    for seed in (0, 42):
        key = jax.random.PRNGKey(seed)
        grouped = cs.sample_flat(key)
        for label, info in cs.params.items():
            ref = draw_dist(info.dist, jax.random.fold_in(key, label_hash(label)))
            assert np.array_equal(np.asarray(ref), np.asarray(grouped[label])), label

    # under jit+vmap (the rand suggest kernel's shape) the reference must
    # be the UNROLLED sampler in the SAME compilation context: XLA fuses
    # `mu + sigma * x` into an fma inside a jitted program but not across
    # eager per-op dispatches, so eager-vs-jit comparisons of the normal
    # families differ in the last ulp (an XLA codegen property, not a
    # sampler property — the eager-vs-eager loop above already pins the
    # grouped/unrolled agreement there).  Grouped vs unrolled inside one
    # jit IS bitwise: same fold_in keys, same formulas, same fusion.
    def unrolled_flat(key):
        return {l: draw_dist(cs.params[l].dist,
                             jax.random.fold_in(key, label_hash(l)))
                for l in cs.labels}

    keys = jax.vmap(
        lambda i: jax.random.fold_in(jax.random.PRNGKey(0), i)
    )(jnp.arange(4, dtype=jnp.uint32))
    outj = jax.jit(jax.vmap(cs.sample_flat))(keys)
    refj = jax.jit(jax.vmap(unrolled_flat))(keys)
    for j in range(4):
        for label in cs.params:
            assert np.array_equal(np.asarray(refj[label][j]),
                                  np.asarray(outj[label][j])), (j, label)
