"""ISSUE 9: the study-axis batched fused tell+ask kernel.

The determinism doctrine, one level up from ISSUE 6's: batching STUDIES
is a scheduling change, not an algorithm change — a cohort of N studies
must propose bit-identically to N independent sequential ``fmin`` runs at
the same per-study seeds, in the replicated layout, in the study-axis-
sharded layout, and across cohort capacity buckets (the graded-cap
machinery slices each slot to a tight power-of-two bucket; padding is
fully masked, so proposals are capacity-invariant).
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu.algos import tpe
from hyperopt_tpu.base import Domain
from hyperopt_tpu.parallel import sharding
from hyperopt_tpu.service import StudyScheduler
from hyperopt_tpu.service.scheduler import _cohort_cap

SPACE = {
    "x": hp.uniform("x", -5, 5),
    "lr": hp.loguniform("lr", -4, 0),
    "k": hp.randint("k", 4),
}

CFG = {"prior_weight": 1.0, "n_EI_candidates": 24, "gamma": 0.25,
       "LF": 25, "ei_select": "argmax", "ei_tau": 1.0, "prior_eps": 0.0}


def obj(d):
    return (d["x"] - 1.0) ** 2 + d["lr"] + 0.1 * d["k"]


def _run_fmin(seed, budget, qn=2, n_startup=4):
    t = Trials()
    fmin(obj, SPACE, algo=functools.partial(tpe.suggest,
                                            n_startup_jobs=n_startup),
         max_evals=budget, max_queue_len=qn, trials=t,
         rstate=np.random.default_rng(seed), show_progressbar=False)
    return [d["misc"]["vals"] for d in t.trials]


def _run_scheduler(seeds, budget, qn=2, n_startup=4):
    sched = StudyScheduler()
    sids = [sched.create_study(SPACE, seed=s, n_startup_jobs=n_startup)
            for s in seeds]
    for _ in range(budget // qn):
        answers = sched.ask_many([(sid, qn) for sid in sids])
        for sid in sids:
            for a in answers[sid]:
                sched.tell(sid, a["tid"], float(obj(a["params"])))
    return [[d["misc"]["vals"] for d in sched._studies[sid].trials]
            for sid in sids], sched


# ---------------------------------------------------------------------------
# the tier-1 determinism pin (replicated layout)
# ---------------------------------------------------------------------------


def test_cohort_bit_identical_to_sequential_fmin():
    """A batched cohort of N studies == N independent sequential fmin runs
    at the same per-study seeds, trial for trial, bit for bit."""
    seeds = [100, 101, 102, 103]
    budget = 12
    expected = [_run_fmin(s, budget) for s in seeds]
    got, _ = _run_scheduler(seeds, budget)
    assert got == expected


def test_cohort_determinism_across_cap_migration():
    """A budget crossing the graded capacity buckets (16 -> 32) migrates
    studies between cohorts mid-run without perturbing the pin."""
    seeds = [7, 8]
    budget = 20  # crosses _cohort_cap's 16-slot bucket at n = 16
    assert _cohort_cap(10) == 16 and _cohort_cap(16) == 32
    expected = [_run_fmin(s, budget) for s in seeds]
    got, sched = _run_scheduler(seeds, budget)
    assert got == expected
    caps = {c.cap for c in sched._cohorts.values()}
    assert 32 in caps  # really migrated to the bigger bucket


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs 8 (virtual) devices")
def test_cohort_sharded_study_axis_bit_identical(monkeypatch):
    """HYPEROPT_TPU_SHARD armed: the study axis shards across the mesh and
    proposals stay bit-identical to the replicated layout — and hence to
    the sequential fmin runs."""
    seeds = list(range(60, 68))  # 8 studies: slots divide the 8-dev mesh
    budget = 10
    expected = [_run_fmin(s, budget) for s in seeds]
    monkeypatch.setenv("HYPEROPT_TPU_SHARD", "8")
    got, _ = _run_scheduler(seeds, budget)
    assert got == expected


# ---------------------------------------------------------------------------
# kernel-level pins
# ---------------------------------------------------------------------------


def _hist_at_cap(cs, cap, n_live, rng):
    vals = {l: np.zeros(cap, np.float32) for l in cs.labels}
    act = {l: np.zeros(cap, bool) for l in cs.labels}
    losses = np.full(cap, np.inf, np.float32)
    has = np.zeros(cap, bool)
    for i in range(n_live):
        for l in cs.labels:
            vals[l][i] = rng.uniform(0.1, 3)
            act[l][i] = True
        losses[i] = rng.uniform()
        has[i] = True
    return {"vals": {l: jnp.asarray(vals[l]) for l in cs.labels},
            "active": {l: jnp.asarray(act[l]) for l in cs.labels},
            "losses": jnp.asarray(losses), "has_loss": jnp.asarray(has)}


def test_proposals_bitwise_capacity_invariant():
    """The graded-cap contract: the fused kernel's proposals do not depend
    on the padded capacity (16 vs 128) — padding is fully masked."""
    dom = Domain(None, SPACE)
    cs = dom.cs
    L = len(cs.labels)
    outs = {}
    for cap in (16, 32, 128):
        dev = _hist_at_cap(cs, cap, n_live=9, rng=np.random.default_rng(3))
        run = tpe._get_suggest_jit(dom, tuple(sorted(CFG.items())), CFG,
                                   donate=False)
        rows = np.zeros((16, 2 * L + 3), np.float32)
        rows[:, -1] = cap
        out = run(dev, rows, tpe._seed_words(99),
                  np.asarray([4, 5, 6, 7], np.uint32))
        outs[cap] = np.asarray(out[1])
    assert np.array_equal(outs[16], outs[32])
    assert np.array_equal(outs[32], outs[128])


def test_batched_kernel_matches_single_study_kernel():
    """build_suggest_batched == the single-study fused program vmapped:
    same fold, same key derivation, same proposals per slot."""
    dom = Domain(None, SPACE)
    cs = dom.cs
    L = len(cs.labels)
    S, cap, B = 4, 32, 2
    rng = np.random.default_rng(11)
    devs = [_hist_at_cap(cs, cap, n_live=5 + s, rng=rng) for s in range(S)]
    rows = np.zeros((S, 16, 2 * L + 3), np.float32)
    rows[:, :, -1] = cap
    # one real pending tell row for slot 0
    rows[0, 0, :L] = 1.5
    rows[0, 0, L:2 * L] = 1.0
    rows[0, 0, 2 * L] = 0.25
    rows[0, 0, 2 * L + 1] = 1.0
    rows[0, 0, 2 * L + 2] = 6.0
    seeds = np.stack([tpe._seed_words(1000 + s) for s in range(S)])
    ids = np.asarray([[3 + s, 9 + s] for s in range(S)], np.uint32)

    single = tpe._get_suggest_jit(dom, tuple(sorted(CFG.items())), CFG,
                                  donate=False)
    expected = [np.asarray(single(devs[s], rows[s], seeds[s], ids[s])[1])
                for s in range(S)]

    stack = jax.tree.map(lambda *xs: jnp.stack(xs), *devs)
    run = tpe.build_suggest_batched(cs, CFG, S, cap, B, donate=False)
    _, packed = run(stack, rows, seeds, ids)
    packed = np.asarray(packed)
    for s in range(S):
        assert np.array_equal(packed[s], expected[s]), s


def test_cohort_donation_folds_in_place():
    """DONATION pin for the study axis: across ticks the stacked history
    buffers keep their addresses — no S×cap copy per wave."""
    sched = StudyScheduler()
    sids = [sched.create_study(SPACE, seed=40 + i, n_startup_jobs=2)
            for i in range(4)]

    def wave():
        answers = sched.ask_many([(sid, 1) for sid in sids])
        for sid in sids:
            for a in answers[sid]:
                sched.tell(sid, a["tid"], float(obj(a["params"])))

    for _ in range(3):
        wave()
    cohort = next(iter(sched._cohorts.values()))
    ptrs = {"losses": cohort._dev["losses"].unsafe_buffer_pointer(),
            "x": cohort._dev["vals"]["x"].unsafe_buffer_pointer()}
    for _ in range(4):
        wave()
        assert cohort._dev["losses"].unsafe_buffer_pointer() == ptrs["losses"]
        assert cohort._dev["vals"]["x"].unsafe_buffer_pointer() == ptrs["x"]


def test_cohort_cache_keyed_on_shape():
    """The cohort-program LRU distinguishes cohort shapes and reports
    hit/miss stats (the ``suggest.cohort_cache`` metrics source)."""
    cs = Domain(None, SPACE).cs
    before = tpe.cohort_cache_stats()
    fn1 = tpe.build_suggest_batched(cs, CFG, 4, 32, 1, donate=False)
    fn2 = tpe.build_suggest_batched(cs, CFG, 4, 32, 1, donate=False)
    assert fn1 is fn2
    fn3 = tpe.build_suggest_batched(cs, CFG, 8, 32, 1, donate=False)
    assert fn3 is not fn1
    after = tpe.cohort_cache_stats()
    assert after["hits"] >= before["hits"] + 1
    assert after["misses"] >= before["misses"] + 1


# ---------------------------------------------------------------------------
# partition rules for the study axis
# ---------------------------------------------------------------------------


def test_study_axis_partition_rules():
    from jax.sharding import PartitionSpec as P

    rules = sharding.suggest_partition_rules(study_axis=True)
    tree = {"hist": {"losses": 0, "has_loss": 0,
                     "vals": {"x": 0}, "active": {"x": 0}},
            "ids": 0, "rows": 0, "seed_words": 0, "packed": 0}
    specs = sharding.match_partition_rules(rules, tree)
    batch = P((sharding.CAND_AXIS,))
    # EVERY cohort leaf leads with the study axis and shards over it
    assert specs["hist"]["losses"] == batch
    assert specs["hist"]["vals"]["x"] == batch
    assert specs["rows"] == batch
    assert specs["seed_words"] == batch
    assert specs["ids"] == batch
    assert specs["packed"] == batch


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs 8 (virtual) devices")
def test_suggest_batched_shardings_build():
    mesh = sharding.suggest_mesh(8)
    in_sh, out_sh = sharding.suggest_batched_shardings(mesh, ("x", "lr"))
    hist_sh, rows_sh, seeds_sh, ids_sh = in_sh
    assert set(hist_sh["vals"]) == {"x", "lr"}
    assert len(out_sh) == 2


def test_cohort_cap_buckets():
    assert _cohort_cap(0) == 16
    assert _cohort_cap(15) == 16
    assert _cohort_cap(16) == 32
    assert _cohort_cap(40) == 64
    assert _cohort_cap(200) == 256
