"""Child process for the WAL crash-resume tier-1 test (test_journal.py).

Drives a store+WAL-backed :class:`StudyScheduler` through ask/tell
traffic with a chaos ``kill@tick`` schedule armed via the environment —
the process SIGKILLs ITSELF mid-wave (inside a cohort-tick dispatch:
after the id allocation and seed draw, before anything journals or
lands).  The parent then resumes on the same store root and pins the
combined history bitwise against an undisturbed reference.

Usage: python _service_child.py <store_root> <n_studies> <budget>
(HYPEROPT_TPU_CHAOS armed by the parent.)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hyperopt_tpu import hp  # noqa: E402
from hyperopt_tpu.service import StudyScheduler  # noqa: E402


def main():
    store_root, n_studies, budget = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
    space = {"x": hp.uniform("x", -5, 5)}
    spec = {"space": {"x": {"dist": "uniform", "args": [-5, 5]}}}
    sched = StudyScheduler(store_root=store_root, max_studies=64)
    sids = [sched.create_study(space, seed=500 + i, n_startup_jobs=3,
                               study_id=f"study-child{i}",
                               space_spec=spec)
            for i in range(n_studies)]
    for _ in range(budget):
        for i, sid in enumerate(sids):
            a = sched.ask(sid)[0]  # chaos kill@tick fires in here
            loss = float((a["params"]["x"] - (i - 1.0)) ** 2)
            sched.tell(sid, a["tid"], loss)
    print("CHILD_FINISHED_WITHOUT_KILL", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
