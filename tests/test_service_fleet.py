"""Replicated serving fleet tests (ISSUE 12): the pinned study→shard
map, collision-proof study-id minting, journal-compaction directory
durability, in-process migration determinism through BOTH paths (drain
handoff AND stale-lease reclaim — each bitwise vs the undisturbed
single-scheduler reference), zombie-holder fencing, 307 routing over
real HTTP with the client's bounded-hop redirect following, steward
rebalance convergence, and the /healthz surface.
"""

import json
import os
import stat
import time

import pytest

from hyperopt_tpu import hp
from hyperopt_tpu.filestore import new_run_id
from hyperopt_tpu.service import (FleetReplica, ServiceClient,
                                  ShardUnavailable, StudyScheduler,
                                  shard_of)
from hyperopt_tpu.service.client import ServiceUnavailable
from hyperopt_tpu.service.journal import StudyJournal
from hyperopt_tpu.service.server import ServiceHTTPServer

SPACE = {"x": hp.uniform("x", -5, 5)}
SPEC = {"x": {"dist": "uniform", "args": [-5, 5]}}


def _replica(root, rid, n_shards=2, lease_ttl=5.0, **kw):
    return FleetReplica(root, n_shards=n_shards, replica_id=rid,
                        addr=f"http://{rid}", lease_ttl=lease_ttl,
                        scheduler_kwargs={"wave_window": 0.0}, **kw)


def _age_lease(replica, shard, sec=60.0):
    path = replica.leases._lease_path(f"shard{shard:04d}")
    t = time.time() - sec
    os.utime(path, (t, t))


def _kill(replica):
    """The SIGKILL analog for an in-process replica: stop heartbeating
    (age every lease + the member record); no drain, no compaction —
    exactly what a killed process leaves behind."""
    for shard in list(replica.schedulers):
        _age_lease(replica, shard)
    os.utime(replica._replica_path(), (time.time() - 600,) * 2)


def _drive(server, sid, n, offset=0.0):
    seq = []
    for _ in range(n):
        status, p = server.handle("POST", "/ask", {"study_id": sid})
        assert status == 200, p
        t = p["trials"][0]
        status, p2 = server.handle("POST", "/tell", {
            "study_id": sid, "tid": t["tid"],
            "loss": float(t["params"]["x"] - offset) ** 2})
        assert status == 200, p2
        seq.append((t["tid"], repr(t["params"]["x"])))
    return seq


def _reference(seed, n, n_startup=2, offset=0.0):
    sched = StudyScheduler(wal=False, max_studies=64)
    sid = sched.create_study(SPACE, seed=seed, n_startup_jobs=n_startup)
    seq = []
    for _ in range(n):
        a = sched.ask(sid)[0]
        sched.tell(sid, a["tid"], float(a["params"]["x"] - offset) ** 2)
        seq.append((a["tid"], repr(a["params"]["x"])))
    return seq


# ---------------------------------------------------------------------------
# the study→shard map & id minting (satellite)
# ---------------------------------------------------------------------------


def test_shard_of_is_pinned():
    # literal pins: re-bucketing would strand every persisted study
    # behind 307s to the wrong owner — the fleet analog of the
    # shard_trials re-bucketing pin in test_membership.py
    assert shard_of("study-000000000000", 8) == 2
    assert shard_of("study-ee45d6db14f9", 8) == 6
    assert shard_of("study-ee45d6db14f9", 1) == 0
    # stable across repeated calls / processes (CRC32, not hash())
    assert shard_of("abc", 4) == shard_of("abc", 4)


def test_new_run_id_unique_dir_redraws_on_collision(tmp_path, monkeypatch):
    draws = [b"\x00" * 6, b"\x00" * 6, b"\x01" * 6]
    monkeypatch.setattr(os, "urandom", lambda n: draws.pop(0))
    first = new_run_id("study", unique_dir=str(tmp_path))
    assert first == "study-000000000000"
    # the second replica draws the SAME 48 bits: mkdir loses, redraw
    second = new_run_id("study", unique_dir=str(tmp_path))
    assert second == "study-010101010101"
    assert (tmp_path / first).is_dir()
    assert (tmp_path / second).is_dir()


def test_new_run_id_without_unique_dir_unchanged(tmp_path):
    rid = new_run_id("study")
    assert rid.startswith("study-") and len(rid) == len("study-") + 12
    assert not os.path.exists(rid)


# ---------------------------------------------------------------------------
# journal compaction directory durability (satellite)
# ---------------------------------------------------------------------------


def test_journal_rewrite_fsyncs_parent_directory(tmp_path, monkeypatch):
    j = StudyJournal(str(tmp_path / "wal.jsonl"))
    j.append({"kind": "admit", "sid": "s"})
    j.sync()
    synced = []
    real_fsync = os.fsync
    monkeypatch.setattr(os, "fsync", lambda fd: (
        synced.append(stat.S_ISDIR(os.fstat(fd).st_mode)),
        real_fsync(fd)))
    j.rewrite([{"kind": "snapshot", "sid": "s"}])
    # the compaction fsynced the file AND the parent directory entry
    # (ext4-ordered rename durability — a crash after os.replace must
    # not resurrect the pre-compaction journal)
    assert True in synced and False in synced
    assert [r["kind"] for r in j.records()] == ["snapshot"]


# ---------------------------------------------------------------------------
# in-process fleet: determinism through both migration paths
# ---------------------------------------------------------------------------


def test_fleet_proposals_bitwise_vs_single_scheduler(tmp_path):
    ra = _replica(str(tmp_path), "ra", n_shards=4)
    ra.join()
    ra.steward_once()
    assert sorted(ra.schedulers) == [0, 1, 2, 3]
    server = ServiceHTTPServer(0, fleet=ra)
    status, p = server.handle("POST", "/study", {
        "space": SPEC, "seed": 42, "n_startup_jobs": 2})
    assert status == 200, p
    seq = _drive(server, p["study_id"], 6)
    assert seq == _reference(42, 6)


def test_drain_handoff_migration_bitwise(tmp_path):
    root = str(tmp_path)
    ra = _replica(root, "ra")
    ra.join()
    ra.steward_once()
    sa = ServiceHTTPServer(0, fleet=ra)
    _, p = sa.handle("POST", "/study", {"space": SPEC, "seed": 7,
                                        "n_startup_jobs": 2})
    sid = p["study_id"]
    seq = _drive(sa, sid, 5)
    assert ra.drain()  # graceful: handoff quiesced + WAL compacted
    rb = _replica(root, "rb")
    rb.join()
    rb.steward_once()
    assert sorted(rb.schedulers) == [0, 1]
    # adoption compacted the chain: ONE epoch file per shard remains
    shard = shard_of(sid, 2)
    assert len(rb.wal_chain(shard)) == 1
    assert rb.epochs[shard] == 2
    sb = ServiceHTTPServer(0, fleet=rb)
    seq += _drive(sb, sid, 4)
    assert seq == _reference(7, 9)


def test_sigkill_reclaim_migration_bitwise(tmp_path):
    root = str(tmp_path)
    ra = _replica(root, "ra")
    ra.join()
    ra.steward_once()
    sa = ServiceHTTPServer(0, fleet=ra)
    _, p = sa.handle("POST", "/study", {"space": SPEC, "seed": 9,
                                        "n_startup_jobs": 2})
    sid = p["study_id"]
    seq = _drive(sa, sid, 5)
    _kill(ra)  # no drain, no compaction — the raw epoch WAL remains
    rb = _replica(root, "rb")
    rb.join()
    rb.steward_once()  # reclaims the stale leases, adopts by replay
    assert sorted(rb.schedulers) == [0, 1]
    assert all(e == 2 for e in rb.epochs.values())
    sb = ServiceHTTPServer(0, fleet=rb)
    seq += _drive(sb, sid, 4)
    assert seq == _reference(9, 9)
    # a told-but-never-compacted study migrated with zero lost tells
    status, tl = sb.handle("GET", f"/study/{sid}/timeline", {})
    assert status == 200
    assert tl["n_told"] == 9


def test_zombie_holder_fenced_after_reclaim(tmp_path):
    """A holder that stalls past the TTL and is reclaimed must stop
    serving within its verification interval — answering 307 to the new
    owner, never stale 200s forever."""
    root = str(tmp_path)
    ra = _replica(root, "ra", lease_ttl=0.8)  # verify every 0.2s
    ra.join()
    ra.steward_once()
    sa = ServiceHTTPServer(0, fleet=ra)
    _, p = sa.handle("POST", "/study", {"space": SPEC, "seed": 3,
                                        "n_startup_jobs": 2})
    sid = p["study_id"]
    _drive(sa, sid, 3)
    _kill(ra)
    rb = _replica(root, "rb", lease_ttl=0.8)
    rb.join()
    rb.steward_once()
    time.sleep(0.3)  # past ra's lease-verification interval
    status, p = sa.handle("POST", "/ask", {"study_id": sid})
    assert status == 307, p
    assert p["location"] == "http://rb"
    assert ra.leases_lost >= 1


def test_unowned_shard_answers_retryable_503(tmp_path):
    ra = _replica(str(tmp_path), "ra")
    # no join/steward: nothing claimed, no ownership table entries
    server = ServiceHTTPServer(0, fleet=ra)
    status, p = server.handle("POST", "/ask", {"study_id": "study-x"})
    assert status == 503, p
    assert p["retry_after"] > 0
    with pytest.raises(ShardUnavailable):
        ra.place_study()


def test_steward_rebalance_converges(tmp_path):
    ra = _replica(str(tmp_path), "ra", n_shards=8)
    ra.join()
    ra.steward_once()
    assert len(ra.schedulers) == 8  # alone: owns the whole keyspace
    rb = _replica(str(tmp_path), "rb", n_shards=8)
    rb.join()
    for _ in range(8):  # handoffs are one-per-sweep (gradual)
        ra.steward_once()
        rb.steward_once()
    assert len(ra.schedulers) == 4
    assert len(rb.schedulers) == 4
    assert ra.handoffs == 4 and rb.adoptions == 4
    # the ownership table routes every shard to exactly one of them
    owners = {s: ra.read_owner(s)["replica"] for s in range(8)}
    assert sorted(owners.values()).count("ra") == 4
    assert sorted(owners.values()).count("rb") == 4


# ---------------------------------------------------------------------------
# ask idempotency (the retried-ask dedupe)
# ---------------------------------------------------------------------------


def test_retried_ask_answers_the_same_trials():
    """An ask whose response was lost (crash/disconnect AFTER the ask
    became durable) is retried with the same ``req`` token and must
    answer the ORIGINAL trials — a fresh seed draw would fork the
    study's proposal stream from its deterministic reference (the
    ask-side analog of 409-on-retried-tell)."""
    sched = StudyScheduler(wal=False, max_studies=16)
    sid = sched.create_study(SPACE, seed=11, n_startup_jobs=2)
    # startup (rand, inline) path
    a1 = sched.ask(sid, req_id="req-a")
    again = sched.ask(sid, req_id="req-a")
    assert [(t["tid"], repr(t["params"]["x"])) for t in a1] \
        == [(t["tid"], repr(t["params"]["x"])) for t in again]
    sched.tell(sid, a1[0]["tid"], 1.0)
    b = sched.ask(sid, req_id="req-b")
    sched.tell(sid, b[0]["tid"], 2.0)
    # TPE (cohort wave) path
    c1 = sched.ask(sid, req_id="req-c")
    c2 = sched.ask(sid, req_id="req-c")
    assert [(t["tid"], repr(t["params"]["x"])) for t in c1] \
        == [(t["tid"], repr(t["params"]["x"])) for t in c2]
    # distinct tokens draw distinct trials; dedupe is counted
    d = sched.ask(sid, req_id="req-d")
    assert d[0]["tid"] != c1[0]["tid"]
    assert sched.metrics.counter("service.asks_deduped").value >= 2


def test_ask_dedupe_survives_wal_resume(tmp_path):
    """The idempotency map rides the WAL (ask records + snapshots), so
    a client retrying into a restarted — or migrated — scheduler still
    gets the original trials."""
    root = str(tmp_path)
    sched = StudyScheduler(store_root=root, max_studies=16)
    sid = sched.create_study(SPACE, seed=13, n_startup_jobs=1,
                             space_spec={"space": SPEC})
    a = sched.ask(sid, req_id="boot-req")
    del sched  # the crash
    resumed = StudyScheduler(store_root=root, max_studies=16)
    again = resumed.ask(sid, req_id="boot-req")
    assert [(t["tid"], repr(t["params"]["x"])) for t in a] \
        == [(t["tid"], repr(t["params"]["x"])) for t in again]


def test_ask_dedupe_survives_fleet_migration(tmp_path):
    root = str(tmp_path)
    ra = _replica(root, "ra")
    ra.join()
    ra.steward_once()
    sa = ServiceHTTPServer(0, fleet=ra)
    _, p = sa.handle("POST", "/study", {"space": SPEC, "seed": 17,
                                        "n_startup_jobs": 1})
    sid = p["study_id"]
    _, p = sa.handle("POST", "/ask", {"study_id": sid, "req": "lost-1"})
    first = p["trials"]
    _kill(ra)
    rb = _replica(root, "rb")
    rb.join()
    rb.steward_once()
    sb = ServiceHTTPServer(0, fleet=rb)
    _, p = sb.handle("POST", "/ask", {"study_id": sid, "req": "lost-1"})
    assert [(t["tid"], t["params"]) for t in p["trials"]] \
        == [(t["tid"], t["params"]) for t in first]


# ---------------------------------------------------------------------------
# /healthz (satellite)
# ---------------------------------------------------------------------------


def test_healthz_fleet_shape(tmp_path):
    ra = _replica(str(tmp_path), "ra", n_shards=2)
    ra.join()
    ra.steward_once()
    server = ServiceHTTPServer(0, fleet=ra)
    server.handle("POST", "/study", {"space": SPEC, "seed": 1})
    status, h = server.handle("GET", "/healthz", {})
    assert status == 200
    assert h["ok"] is True and h["draining"] is False
    assert h["replica"] == "ra"
    assert h["n_shards"] == 2
    assert h["shards_held"] == [0, 1]
    for shard in ("0", "1"):
        entry = h["shards"][shard]
        assert entry["epoch"] == 1
        assert set(entry["wal"]) == {"path", "appends", "syncs",
                                     "compactions"}
    assert h["wal_sync_errors"] >= 0
    assert "replicas" in h and "adoptions" in h
    json.dumps(h)  # machine-readable end to end


def test_top_renders_fleet_row(tmp_path):
    """obs.top's service view grows a FLEET row from the snapshot's
    fleet block (replica, shards held, peers, adoption traffic)."""
    from hyperopt_tpu.obs.top import render_frame

    ra = _replica(str(tmp_path), "ra", n_shards=2)
    ra.join()
    ra.steward_once()
    server = ServiceHTTPServer(0, fleet=ra)
    server.handle("POST", "/study", {"space": SPEC, "seed": 1})
    snap = server.snapshot_dict()
    frame = render_frame([("replica-a", snap)], {})
    assert "FLEET" in frame
    assert "ra" in frame
    assert "shards 2/2" in frame


def test_healthz_single_server_shape():
    server = ServiceHTTPServer(0, scheduler=StudyScheduler(wal=False))
    status, h = server.handle("GET", "/healthz", {})
    assert status == 200
    assert h["ok"] is True
    assert h["shards_held"] == [] and h["n_shards"] is None
    json.dumps(h)


# ---------------------------------------------------------------------------
# 307 routing over real HTTP + the client's redirect following
# ---------------------------------------------------------------------------


def test_http_307_routing_redirect_cache_and_location_header(tmp_path):
    root = str(tmp_path)
    ra = _replica(root, "ra", lease_ttl=10.0)
    rb = _replica(root, "rb", lease_ttl=10.0)
    sa = ServiceHTTPServer(0, fleet=ra)
    sb = ServiceHTTPServer(0, fleet=rb)
    assert sa.start() and sb.start()
    try:
        ra.set_addr(sa.url)
        rb.set_addr(sb.url)
        ra.join()
        rb.join()
        for _ in range(4):
            ra.steward_once()
            rb.steward_once()
        assert len(ra.schedulers) == 1 and len(rb.schedulers) == 1

        cb = ServiceClient(sb.url, key=2)
        sid_b = cb.create_study(space=SPEC, seed=9, n_startup_jobs=2)
        # talk to B's study THROUGH A: one 307, followed transparently
        ca = ServiceClient(sa.url, key=1)
        t = ca.ask(sid_b)[0]
        assert ca.redirects == 1
        assert ca.tell(sid_b, t["tid"], 0.5) == {"duplicate": False}
        # the resolved owner is cached: no second redirect
        ca.ask(sid_b)
        assert ca.redirects == 1
        # the raw HTTP answer carries the Location header too
        import urllib.request

        req = urllib.request.Request(
            sa.url + "/ask",
            data=json.dumps({"study_id": sid_b}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            urllib.request.urlopen(req, timeout=30)
            raise AssertionError("expected a 307")
        except urllib.error.HTTPError as e:
            assert e.code == 307
            assert e.headers["Location"] == sb.url
    finally:
        sa.stop()
        sb.stop()


def test_client_bounded_hops_degrade_to_retry(monkeypatch):
    """A redirect loop (two replicas pointing at each other — a stale
    ownership table) must exhaust the hop budget and degrade to plain
    retry-with-backoff, not spin forever."""
    client = ServiceClient("http://a", retry=2, sleep=lambda s: None)
    calls = []

    def fake_once(method, path, body):
        calls.append(client.url)
        other = "http://b" if client.url == "http://a" else "http://a"
        return 307, {"ok": False, "location": other}, None

    monkeypatch.setattr(client, "_once", fake_once)
    with pytest.raises(ServiceUnavailable):
        client.request("POST", "/ask", {"study_id": "s"})
    # each retry attempt burns at most max_hops redirects
    assert len(calls) <= (client.max_hops + 1) * 4
    assert client.redirects > client.max_hops


def test_client_rotates_seed_urls_on_connection_error(monkeypatch):
    client = ServiceClient(["http://dead", "http://live"], retry=3,
                           sleep=lambda s: None)
    bases = []

    def fake_once(method, path, body):
        bases.append(client.url)
        if client.url == "http://dead":
            raise ConnectionRefusedError("refused")
        return 200, {"ok": True, "trials": []}, None

    monkeypatch.setattr(client, "_once", fake_once)
    status, payload = client.request("POST", "/ask", {"study_id": "s"})
    assert status == 200
    assert bases == ["http://dead", "http://live"]
