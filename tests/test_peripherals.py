"""Peripheral-module tests.

Parity targets (SURVEY.md §4 table): ``hyperopt/tests/test_plotting.py``
(Agg-backend smoke), ``test_criteria.py`` (closed-form checks),
``test_progress.py``, ``test_utils.py``, plus worker-CLI argument handling
and the graphviz DOT renderer.
"""

import math
import os

import numpy as np
import pytest

from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu.algos import rand

matplotlib = pytest.importorskip("matplotlib")
matplotlib.use("Agg")  # headless backend, the reference test doctrine


@pytest.fixture(scope="module")
def run_trials():
    t = Trials()
    fmin(
        lambda d: (d["x"] - 1.0) ** 2 + 0.1 * d["n"],
        {"x": hp.uniform("x", -5, 5), "n": hp.randint("n", 3)},
        algo=rand.suggest, max_evals=15, trials=t,
        rstate=np.random.default_rng(0), show_progressbar=False,
    )
    return t


# ---------------------------------------------------------------------------
# plotting (Agg smoke — reference: tests/test_plotting.py)
# ---------------------------------------------------------------------------


def test_main_plot_history(run_trials):
    from hyperopt_tpu.plotting import main_plot_history

    fig = main_plot_history(run_trials, do_show=False)
    assert fig.axes and fig.axes[0].get_ylabel() == "loss"
    matplotlib.pyplot.close(fig)


def test_main_plot_histogram(run_trials):
    from hyperopt_tpu.plotting import main_plot_histogram

    fig = main_plot_histogram(run_trials, do_show=False)
    assert fig.axes
    matplotlib.pyplot.close(fig)


def test_main_plot_vars(run_trials):
    from hyperopt_tpu.plotting import main_plot_vars

    fig = main_plot_vars(run_trials, do_show=False)
    # one subplot per hyperparameter (x and n) at minimum
    assert len([a for a in fig.axes if a.get_title() in ("x", "n")]) == 2
    matplotlib.pyplot.close(fig)


def test_plots_tolerate_empty_trials():
    from hyperopt_tpu.plotting import (
        main_plot_histogram, main_plot_history, main_plot_vars)

    t = Trials()
    for fn in (main_plot_history, main_plot_histogram, main_plot_vars):
        fig = fn(t, do_show=False)
        matplotlib.pyplot.close(fig)


# ---------------------------------------------------------------------------
# criteria vs closed form (reference: tests/test_criteria.py)
# ---------------------------------------------------------------------------


def test_ei_empirical_matches_definition():
    from hyperopt_tpu.criteria import EI_empirical

    rng = np.random.default_rng(0)
    s = rng.normal(size=4096)
    got = float(EI_empirical(s, 0.5))
    want = np.mean(np.maximum(s - 0.5, 0.0))
    assert got == pytest.approx(want, rel=1e-5)


def test_ei_gaussian_matches_monte_carlo():
    from hyperopt_tpu.criteria import EI_gaussian

    rng = np.random.default_rng(1)
    mean, var, thresh = 0.3, 1.7, 1.0
    s = rng.normal(mean, math.sqrt(var), size=2_000_000)
    mc = np.mean(np.maximum(s - thresh, 0.0))
    assert float(EI_gaussian(mean, var, thresh)) == pytest.approx(mc, rel=5e-3)


def test_log_ei_gaussian_consistent_and_tail_stable():
    from hyperopt_tpu.criteria import EI_gaussian, logEI_gaussian

    # moderate regime: logEI == log(EI)
    v = float(logEI_gaussian(0.0, 1.0, 1.0))
    assert v == pytest.approx(math.log(float(EI_gaussian(0.0, 1.0, 1.0))), rel=1e-5)
    # deep tail: naive EI underflows to 0, logEI must stay finite and ordered
    far = float(logEI_gaussian(0.0, 1.0, 15.0))
    farther = float(logEI_gaussian(0.0, 1.0, 20.0))
    assert np.isfinite(far) and np.isfinite(farther) and farther < far


def test_ucb():
    from hyperopt_tpu.criteria import UCB

    assert float(UCB(1.0, 4.0, 2.0)) == pytest.approx(5.0)


# ---------------------------------------------------------------------------
# progress (reference: tests/test_progress.py)
# ---------------------------------------------------------------------------


def test_progress_callback_selection():
    from hyperopt_tpu.progress import (
        get_progress_callback, no_progress_callback, tqdm_progress_callback)

    assert get_progress_callback(True) is tqdm_progress_callback
    assert get_progress_callback(False) is no_progress_callback
    custom = no_progress_callback
    assert get_progress_callback(custom) is custom


def test_progress_contexts_update_and_postfix():
    from hyperopt_tpu.progress import no_progress_callback, tqdm_progress_callback

    with no_progress_callback(initial=0, total=10) as ctx:
        ctx.update(3)
        ctx.postfix = "best: 1.0"
    with tqdm_progress_callback(initial=0, total=10) as ctx:
        ctx.update(3)
        ctx.postfix = "best: 1.0"
        assert "best" in str(ctx.postfix)


# ---------------------------------------------------------------------------
# utils (reference: tests/test_utils.py)
# ---------------------------------------------------------------------------


def test_import_tokens_and_json_call():
    from hyperopt_tpu.utils import import_tokens, json_call

    assert import_tokens(["math", "sqrt"]) is math.sqrt
    assert json_call("math.sqrt", (9.0,)) == 3.0
    assert json_call(("math.pow", [2.0, 3.0])) == 8.0


def test_get_most_recent_inds():
    from hyperopt_tpu.utils import get_most_recent_inds

    docs = [
        {"_id": 0, "version": 0},
        {"_id": 0, "version": 1},
        {"_id": 1, "version": 0},
        {"_id": 2, "version": 0},
        {"_id": 2, "version": 2},
    ]
    inds = sorted(get_most_recent_inds(docs))
    assert inds == [1, 2, 4]


def test_fast_isin():
    from hyperopt_tpu.utils import fast_isin

    got = fast_isin([1, 2, 3, 4], [2, 4])
    assert got.tolist() == [False, True, False, True]


def test_temp_dir_and_working_dir(tmp_path):
    from hyperopt_tpu.utils import temp_dir, working_dir

    target = tmp_path / "scratch" / "deep"
    with temp_dir(str(target), erase_after=True):
        assert target.is_dir()
        with working_dir(str(target)):
            assert os.getcwd() == str(target)
    assert not target.exists()


def test_get_closest_dir(tmp_path):
    from hyperopt_tpu.utils import get_closest_dir

    closest, missing = get_closest_dir(str(tmp_path / "a" / "b"))
    assert closest == str(tmp_path)
    assert missing == "a"


# ---------------------------------------------------------------------------
# worker CLI arg handling (reference: mongoexp main_worker CLI tests)
# ---------------------------------------------------------------------------


def test_worker_cli_requires_store(capsys):
    from hyperopt_tpu.worker import main

    with pytest.raises(SystemExit) as e:
        main([])
    assert e.value.code == 2
    assert "--store" in capsys.readouterr().err


def test_worker_cli_reserve_timeout_exits_zero(tmp_path):
    from hyperopt_tpu.worker import main

    rc = main(["--store", str(tmp_path / "s"), "--reserve-timeout", "0.2",
               "--poll-interval", "0.05"])
    assert rc == 0  # empty store: clean reserve-timeout exit


def test_worker_cli_rejects_unknown_flag(tmp_path):
    from hyperopt_tpu import worker

    with pytest.raises(SystemExit):
        worker.main(["--store", str(tmp_path), "--no-such-flag"])


# ---------------------------------------------------------------------------
# graphviz DOT renderer (reference: hyperopt/graphviz.py)
# ---------------------------------------------------------------------------


def test_dot_hyperparameters_renders_all_nodes():
    # the real module plus its pre-rename back-compat alias
    import hyperopt_tpu.graphviz as gv
    from hyperopt_tpu.graphviz_mod import dot_hyperparameters

    assert gv.dot_hyperparameters is dot_hyperparameters

    space = {
        "lr": hp.loguniform("lr", -6, 0),
        "arch": hp.choice("arch", [{"w": hp.uniform("w", 0, 1)}, "none"]),
    }
    dot = dot_hyperparameters(space)
    assert dot.startswith("digraph {") and dot.endswith("}")
    for frag in ("lr", "choice arch", "loguniform", "uniform"):
        assert frag in dot, f"{frag!r} missing from DOT output"


def test_stdout_redirect_through_tqdm(capsys):
    # reference std_out_err_redirect_tqdm.py: prints inside the bar context
    # go through tqdm.write without crashing or being swallowed
    import sys

    from hyperopt_tpu.std_out_err_redirect_tqdm import (
        DummyTqdmFile, std_out_err_redirect_tqdm)

    with std_out_err_redirect_tqdm() as orig_stdout:
        assert isinstance(sys.stdout, DummyTqdmFile)
        print("line1")
        print("line2")
        sys.stdout.flush()
    assert sys.stdout is orig_stdout  # restored on exit
    out = capsys.readouterr()
    combined = out.out + out.err
    # consecutive prints must stay on separate lines (tqdm.write supplies
    # the newline the redirect swallows from print's bare-"\n" write)
    assert "line1\n" in combined and "line2\n" in combined
    assert "line1line2" not in combined


def test_progressbar_survives_printing_objective():
    from hyperopt_tpu.algos import rand as _rand

    t = Trials()
    def noisy(d):
        print("objective says hi")
        return d["x"] ** 2

    fmin(noisy, {"x": hp.uniform("x", -5, 5)}, algo=_rand.suggest,
         max_evals=5, trials=t, rstate=np.random.default_rng(0),
         show_progressbar=True)
    assert len(t) == 5
