"""Cold-start compile plane (ISSUE 14): warming admission, background
compilation, the census kernel bank, and widened cohort programs.

The determinism doctrine carried from ISSUEs 9/10/12: everything the
plane does must either leave proposals bit-identical (disarmed path,
bank warms, padding lanes) or be RECORDED so replay regenerates it
bit-identically (warming asks journal ``algo:"rand"`` exactly like the
degrade floor).  The warming WINDOW itself is wall-clock dependent (a
program is ready when XLA finishes), so the tests that need determinism
pin it with :class:`GatedPlane` — a plane whose readiness answers are a
deterministic schedule rather than a race against the compiler.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hyperopt_tpu import hp
from hyperopt_tpu.algos import tpe
from hyperopt_tpu.service.compile_plane import (CompilePlane,
                                                SignatureCensus,
                                                census_path_for)
from hyperopt_tpu.service.scheduler import StudyScheduler
from hyperopt_tpu.service.spacespec import space_from_spec
from hyperopt_tpu.spaces import compile_space

WIRE = {"x": {"dist": "uniform", "args": [-3, 3]},
        "y": {"dist": "loguniform", "args": [-4, 1]}}

CFG = {"prior_weight": 1.0, "n_EI_candidates": 24, "gamma": 0.25,
       "LF": 25, "ei_select": "argmax", "ei_tau": 1.0, "prior_eps": 0.0}


class GatedPlane(CompilePlane):
    """Deterministic warming window: the first ``n_cold`` readiness
    probes answer cold (enqueueing as usual); after that the plane
    drains its queue synchronously before answering, so the schedule of
    warming-vs-device waves is a pure function of the probe count."""

    def __init__(self, n_cold, census_path=None):
        super().__init__(census_path=census_path)
        self.n_cold = n_cold

    def ready_for(self, key, K, job=None, job_factory=None):
        if self.n_cold > 0:
            self.n_cold -= 1
            super().ready_for(key, K, job=job, job_factory=job_factory)
            return False
        if not super().ready_for(key, K, job=job,
                                 job_factory=job_factory):
            self.drain(timeout=300)
            return super().ready_for(key, K)
        return True


def drive(sched, sid, n, losses=None, collect=None):
    losses = losses if losses is not None else iter(
        float(np.sin(i * 0.73)) for i in range(10 * n))
    for _ in range(n):
        answers = sched.ask(sid)
        if collect is not None:
            collect.append(answers[0])
        sched.tell(sid, answers[0]["tid"], loss=next(losses))


def trial_vals(sched, sid):
    st = sched._studies[sid]
    return [(d["tid"],
             {k: v[0] for k, v in d["misc"]["vals"].items() if v})
            for d in st.trials._dynamic_trials]


# ---------------------------------------------------------------------------
# warming semantics
# ---------------------------------------------------------------------------


def test_warming_flagged_then_promoted_at_wave_boundary():
    plane = GatedPlane(2)
    sched = StudyScheduler(compile_plane=plane, wave_window=0.0)
    sid = sched.create_study(space_from_spec(WIRE), seed=3,
                             n_startup_jobs=1)
    answers = []
    drive(sched, sid, 5, collect=answers)
    # ask 0: startup rand — not warming; asks 1, 2: warming-flagged
    # rand; asks 3+: promoted TPE (the gate opens at probe 3)
    assert "warming" not in answers[0]
    for a in answers[1:3]:
        assert a["warming"] is True and a["algo"] == "rand"
    for a in answers[3:]:
        assert "warming" not in a and "algo" not in a
    st = sched._studies[sid]
    assert st.warming is False
    events = [e["event"] for e in st.events]
    assert "warming" in events and "promote" in events
    # promotion happens AT a wave boundary: the promote event carries
    # the wave the first device tick served
    promo = next(e for e in st.events if e["event"] == "promote")
    assert promo["wave"] is not None
    assert st.status_dict()["warming"] is False
    plane.stop()


def test_warming_asks_journal_algo_rand(tmp_path):
    plane = GatedPlane(1)
    sched = StudyScheduler(compile_plane=plane, wave_window=0.0,
                           store_root=str(tmp_path))
    sid = sched.create_study(space_from_spec(WIRE), seed=3,
                             n_startup_jobs=1,
                             space_spec={"space": WIRE})
    drive(sched, sid, 3)
    recs = [r for r in sched.journal.records() if r.get("kind") == "ask"]
    # ask 0 startup rand, ask 1 warming rand, ask 2 tpe
    assert [r["algo"] for r in recs] == ["rand", "rand", "tpe"]
    plane.stop()


def test_warming_crash_resume_bit_identical(tmp_path):
    """The acceptance pin: a warming→crash→resume run replays
    bit-identically vs an uninterrupted one (same deterministic warming
    window), with the resumed side's programs warmed from the census
    bank so its post-resume asks are device-served like the
    reference's."""
    def run(root, crash_after=None):
        sched = StudyScheduler(
            store_root=root, wave_window=0.0,
            compile_plane=GatedPlane(2, census_path_for(root)))
        sid = sched.create_study(space_from_spec(WIRE), seed=5,
                                 study_id="study-fixed",
                                 space_spec={"space": WIRE},
                                 n_startup_jobs=2)
        losses = iter(float(x) for x in np.sin(np.arange(40) * 0.73))
        for i in range(8):
            t = sched.ask(sid)
            sched.tell(sid, t[0]["tid"], loss=next(losses))
            if crash_after is not None and i == crash_after:
                return sched, losses
        return sched, losses

    ref_root = str(tmp_path / "ref")
    crash_root = str(tmp_path / "crash")
    os.makedirs(ref_root), os.makedirs(crash_root)
    s_ref, _ = run(ref_root)
    ref = trial_vals(s_ref, "study-fixed")
    assert any(e["event"] == "warming"
               for e in s_ref._studies["study-fixed"].events)

    _, losses = run(crash_root, crash_after=5)  # scheduler dropped = crash
    plane = CompilePlane(census_path=census_path_for(crash_root))
    warmed, _ = plane.warm_from_census()
    assert warmed >= 1  # the census round-tripped the cohort key
    resumed = StudyScheduler(store_root=crash_root, wave_window=0.0,
                             compile_plane=plane)
    assert "study-fixed" in resumed._studies
    post = []
    for _ in range(6, 8):
        t = resumed.ask("study-fixed")
        post.append(t[0])
        resumed.tell("study-fixed", t[0]["tid"], loss=next(losses))
    # bank-warmed: the resumed side never re-enters warming
    assert not any(a.get("warming") for a in post)
    assert ref == trial_vals(resumed, "study-fixed")
    plane.stop()


def test_disarmed_scheduler_has_no_plane_and_no_thread():
    import threading

    before = {t.name for t in threading.enumerate()}
    sched = StudyScheduler(wave_window=0.0)
    assert sched.compile_plane is None
    sid = sched.create_study({"x": hp.uniform("x", 0, 1)}, seed=0,
                             n_startup_jobs=1)
    drive(sched, sid, 3)
    after = {t.name for t in threading.enumerate()}
    assert not any("compile-plane" in n for n in after - before)


def test_replay_bypasses_warming_gate(tmp_path):
    """A WAL record that says "tpe" must regenerate through tpe even on
    a stone-cold plane — replay compiles synchronously, it never
    substitutes the rand floor (that would fork the proposal stream)."""
    root = str(tmp_path)
    sched = StudyScheduler(store_root=root, wave_window=0.0,
                           compile_plane=GatedPlane(1, None))
    sid = sched.create_study(space_from_spec(WIRE), seed=9,
                             n_startup_jobs=1,
                             space_spec={"space": WIRE})
    drive(sched, sid, 4)
    ref = trial_vals(sched, sid)
    # wipe the per-study store so replay must REGENERATE the asks, on a
    # fresh scheduler whose plane reports everything cold forever
    import shutil

    shutil.rmtree(os.path.join(root, sid))

    class NeverReady(CompilePlane):
        def ready_for(self, key, K, job=None, job_factory=None):
            return False

    resumed = StudyScheduler(store_root=root, wave_window=0.0,
                             compile_plane=NeverReady())
    assert trial_vals(resumed, sid) == ref


# ---------------------------------------------------------------------------
# census + kernel bank
# ---------------------------------------------------------------------------


def test_census_appends_and_aggregates(tmp_path):
    path = str(tmp_path / "census.jsonl")
    c = SignatureCensus(path)
    for _ in range(10):
        c.note({"space": WIRE}, CFG, 16, 1, 1)
    c.note({"zoo": "quadratic1"}, CFG, 16, 2, 1)
    c.note(None, CFG, 16, 1, 1)  # unresumable: never recorded
    entries = SignatureCensus(path).read()
    assert len(entries) == 2
    # most-used first, max count wins across milestone appends
    assert entries[0]["spec"] == {"space": WIRE}
    assert entries[0]["count"] == 8  # milestones 1 and 8 appended
    assert entries[1]["spec"] == {"zoo": "quadratic1"}


def test_census_write_failure_is_nonfatal(tmp_path):
    c = SignatureCensus(str(tmp_path / "no" / "such" / "dir" / "c.jsonl"))
    for _ in range(3):
        c.note({"space": WIRE}, CFG, 16, 1, 1)  # warns once, never raises
    assert SignatureCensus(c.path).read() == []


def test_bank_warm_marks_ready_without_live_traffic(tmp_path):
    path = str(tmp_path / "census.jsonl")
    SignatureCensus(path).note({"space": WIRE}, CFG, 16, 1, 1)
    plane = CompilePlane(census_path=path)
    warmed, enqueued = plane.warm_from_census(top_n=8)
    assert (warmed, enqueued) == (1, 0)
    cs = compile_space(space_from_spec(WIRE))
    key, _ = plane.make_job(cs, {"space": WIRE}, CFG, 1, 16, 1,
                            donate=tpe._donation_enabled())
    assert plane.ready_for(key, 1) is True
    assert plane.bank_stats() == {"keys": 1, "hits": 1}
    plane.stop()


def test_ready_demotes_on_lru_eviction(tmp_path):
    """An LRU-evicted program must demote to warming (re-enqueue), not
    let the next tick compile synchronously on the serving path."""
    plane = CompilePlane()
    # a signature no other test (or suite in this process) compiles, so
    # the cohort LRU genuinely lacks it
    cs = compile_space({"zz": hp.uniform("zz", -3.123, 3.077)})
    key, job = plane.make_job(cs, None, CFG, 1, 16, 1, donate=True)
    plane.mark_ready(key, 1)
    # the program is NOT in the cohort LRU (never built): readiness
    # must answer False and re-enqueue
    assert not tpe.cohort_cache_contains(key)
    assert plane.ready_for(key, 1, job=job) is False
    plane.stop()


# ---------------------------------------------------------------------------
# widened cohort programs
# ---------------------------------------------------------------------------


def _mk_history(cs, cap=16, n=10, seed=0):
    rng = np.random.default_rng(seed)
    hist = {
        "vals": {l: np.zeros(cap, np.float32) for l in cs.labels},
        "active": {l: np.zeros(cap, bool) for l in cs.labels},
        "losses": np.full(cap, np.inf, np.float32),
        "has_loss": np.zeros(cap, bool),
    }
    for i in range(n):
        for l in cs.labels:
            fam = cs.params[l].dist.family
            if fam in ("randint", "uniformint", "categorical"):
                hist["vals"][l][i] = rng.integers(0, 3)
            else:
                hist["vals"][l][i] = abs(rng.standard_normal()) + 0.01
            hist["active"][l][i] = True
        hist["losses"][i] = rng.standard_normal()
        hist["has_loss"][i] = True
    return hist


WIDE_SPACE = {
    "lr": hp.loguniform("lr", -5, 0),
    "l2": hp.loguniform("l2", -8, 0),
    "mom": hp.uniform("mom", 0.0, 0.98),
    "n": hp.normal("n", 0.0, 1.0),
    "layers": hp.randint("layers", 1, 5),
    "opt": hp.choice("opt", [0, 1, 2]),
}


def test_widened_profile_identity_and_compatibility():
    cs = compile_space(WIDE_SPACE)
    prof_a = tpe.widened_profile(cs)
    assert prof_a is not None
    # a DIFFERENT space with the same shape multiset (other labels,
    # other bounds, other declaration order) shares the profile — that
    # is the program-sharing contract
    cs_b = compile_space({
        "w": hp.uniform("w", -9, 9),
        "a": hp.loguniform("a", -2, 2),
        "b": hp.loguniform("b", -1, 0),
        "g": hp.normal("g", 5.0, 2.0),
        "k": hp.randint("k", 10, 14),
        "c": hp.choice("c", ["x", "y", "z"]),
    })
    prof_b = tpe.widened_profile(cs_b)
    assert prof_a[0] == prof_b[0]
    assert (tpe.cohort_key_wide(prof_a[0], CFG, 1, 16, 1)
            == tpe.cohort_key_wide(prof_b[0], CFG, 1, 16, 1))
    # conditional spaces cannot widen
    cond = compile_space(hp.choice("arch", [
        {"width": hp.uniformint("width", 1, 8)}, {"fixed": 3}]))
    assert tpe.widened_profile(cond) is None


def test_widened_propose_bitwise_vs_group_all_jit():
    """The widening pin: the profile-keyed program (params + hashes as
    runtime inputs, positional slots, padding lanes) proposes BIT-
    IDENTICALLY to the unwidened grouped pipeline (``group="all"``)
    under jit — traced statics change nothing, padding lanes touch
    nothing."""
    cs = compile_space(WIDE_SPACE)
    profile, slots = tpe.widened_profile(cs)
    wp = tpe.widened_params(cs, profile, slots)
    hist = _mk_history(cs)
    key = jax.random.PRNGKey(7)

    ref = jax.jit(tpe.build_propose(cs, CFG, group="all"))(
        {"vals": {l: jnp.asarray(hist["vals"][l]) for l in cs.labels},
         "active": {l: jnp.asarray(hist["active"][l])
                    for l in cs.labels},
         "losses": jnp.asarray(hist["losses"]),
         "has_loss": jnp.asarray(hist["has_loss"])}, key)

    W = sum(e[-1] for e in profile)
    cap = 16
    vals_w = np.zeros((W, cap), np.float32)
    act_w = np.zeros((W, cap), bool)
    pos = {}
    off = 0
    for entry, ls in zip(profile, slots):
        for i, l in enumerate(ls):
            pos[l] = off + i
            vals_w[off + i] = hist["vals"][l]
            act_w[off + i] = hist["active"][l]
        off += entry[-1]
    out = np.asarray(jax.jit(tpe.build_propose_wide(profile, CFG))(
        {"vals": jnp.asarray(vals_w), "active": jnp.asarray(act_w),
         "losses": jnp.asarray(hist["losses"]),
         "has_loss": jnp.asarray(hist["has_loss"])},
        jax.tree_util.tree_map(jnp.asarray, wp), key))
    for l in cs.labels:
        assert np.array_equal(np.float32(np.asarray(ref[l])),
                              np.float32(out[pos[l]])), l


def test_widened_padding_invariance():
    """The space-padding extension of the cap-invariance pin: widening a
    group's slot axis with EXTRA inert lanes leaves every real label's
    proposal bitwise unchanged (vmap lanes are independent; padding
    outputs are discarded)."""
    cs = compile_space({"a": hp.uniform("a", -1, 1),
                        "b": hp.uniform("b", 0, 5)})
    profile, slots = tpe.widened_profile(cs)
    assert profile == (("num", False, True, 2),)
    hist = _mk_history(cs)
    key = jax.random.PRNGKey(11)
    cap = 16

    def run_with(profile_w):
        wp = tpe.widened_params(cs, profile_w, slots)
        W = profile_w[0][-1]
        vals_w = np.zeros((W, cap), np.float32)
        act_w = np.zeros((W, cap), bool)
        for i, l in enumerate(slots[0]):
            vals_w[i] = hist["vals"][l]
            act_w[i] = hist["active"][l]
        return np.asarray(jax.jit(
            tpe.build_propose_wide(profile_w, CFG))(
            {"vals": jnp.asarray(vals_w), "active": jnp.asarray(act_w),
             "losses": jnp.asarray(hist["losses"]),
             "has_loss": jnp.asarray(hist["has_loss"])},
            jax.tree_util.tree_map(jnp.asarray, wp), key))[:2]

    tight = run_with((("num", False, True, 2),))
    padded = run_with((("num", False, True, 8),))  # 6 inert lanes
    assert np.array_equal(tight, padded)


def test_widened_cohort_end_to_end_shares_programs():
    """Through the real scheduler: two compatible spaces tick through
    ONE compiled widened program (zero extra cohort-cache misses for
    the second), each study deterministic across repeat runs."""
    space_a = {"lr": hp.loguniform("lr", -5, 0),
               "mom": hp.uniform("mom", 0, 1)}
    space_b = {"alpha": hp.loguniform("alpha", -3, -1),
               "beta": hp.uniform("beta", -2, 2)}

    def drive_widened(space, seed):
        sched = StudyScheduler(wave_window=0.0, widen=True)
        sid = sched.create_study(space, seed=seed, n_startup_jobs=2)
        out = []
        for i in range(6):
            t = sched.ask(sid)
            out.append(t[0]["params"])
            sched.tell(sid, t[0]["tid"], loss=float(np.sin(i * 1.7)))
        return out

    v1 = drive_widened(space_a, 7)
    v2 = drive_widened(space_a, 7)
    assert v1 == v2
    m0 = tpe.cohort_cache_stats()["misses"]
    drive_widened(space_b, 11)  # compatible: reuses space_a's program
    assert tpe.cohort_cache_stats()["misses"] == m0


def test_widen_defaults_off_and_env_arms(monkeypatch):
    sched = StudyScheduler(wave_window=0.0)
    assert sched.widen is False
    monkeypatch.setenv("HYPEROPT_TPU_COMPILE_WIDEN", "1")
    sched2 = StudyScheduler(wave_window=0.0)
    assert sched2.widen is True


# ---------------------------------------------------------------------------
# scrape-plane visibility (the cache-counter satellite)
# ---------------------------------------------------------------------------


def test_compile_gauges_on_metrics_and_snapshot():
    from hyperopt_tpu.obs.serve import prometheus_text
    from hyperopt_tpu.service.server import ServiceHTTPServer

    plane = GatedPlane(1)
    sched = StudyScheduler(compile_plane=plane, wave_window=0.0)
    server = ServiceHTTPServer(0, scheduler=sched, slo=False)
    sid = sched.create_study(space_from_spec(WIRE), seed=3,
                             n_startup_jobs=1)
    drive(sched, sid, 3)
    snap = server.snapshot_dict()
    assert snap["compile"]["compiled"] >= 1
    assert snap["compile"]["warming_studies"] == 0
    assert "hits" in snap["cohort_cache"] and "hits" in snap["jit_cache"]
    server._refresh_compile_gauges()
    text = prometheus_text()
    for family in ("service_compile_cohort_cache_hits",
                   "service_compile_jit_cache_size",
                   "service_compile_warming_studies",
                   "service_compile_queue_depth"):
        assert family in text, family
    # the /ask response carries the warming flag over the wire shape
    status, payload = server.handle("POST", "/ask", {"study_id": sid})
    assert status == 200 and "warming" not in payload
    plane.stop()


def test_pre_issue14_wal_resumes_unchanged(tmp_path):
    """A journal with no ISSUE-14-era traffic (no warming records, no
    census) resumes bit-identically on a plane-armed scheduler — the
    plane only ever gates LIVE dispatch."""
    root = str(tmp_path)
    sched = StudyScheduler(store_root=root, wave_window=0.0)
    sid = sched.create_study(space_from_spec(WIRE), seed=13,
                             n_startup_jobs=1,
                             space_spec={"space": WIRE})
    drive(sched, sid, 4)
    ref = trial_vals(sched, sid)
    resumed = StudyScheduler(store_root=root, wave_window=0.0,
                             compile_plane=CompilePlane())
    assert trial_vals(resumed, sid) == ref
    resumed.compile_plane.stop()
