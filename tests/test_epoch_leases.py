"""Long-lived epoch-lease tests (ISSUE 12): claim exclusivity + epoch
monotonicity, heartbeat/expiry/reclaim ordering under clock skew (fake
clock via ``os.utime`` — lease age IS file mtime, exactly like
tests/test_membership.py), two reclaimers racing, and the
owner-verified heartbeat/release that fences a stalled holder.
"""

import os
import time

from hyperopt_tpu.obs.metrics import MetricsRegistry
from hyperopt_tpu.parallel.membership import EpochLeases as _EpochLeases


def EpochLeases(root, **kw):  # noqa: N802 - drop-in with isolated metrics
    """The class under test, with a PRIVATE metrics registry per
    instance: the default shares the process-global "fleet" namespace,
    and these tests' reclaim/contention counts must not bleed into
    tests/test_membership.py's exact-value assertions (or vice versa)."""
    kw.setdefault("metrics", MetricsRegistry("epoch-leases-test"))
    return _EpochLeases(root, **kw)


def _age(leases, name, sec):
    """Fake clock: push a lease's mtime ``sec`` seconds into the past
    (clock skew between a holder and a reclaimer looks identical — the
    reclaimer only ever sees the mtime)."""
    path = leases._lease_path(name)
    t = time.time() - sec
    os.utime(path, (t, t))


# ---------------------------------------------------------------------------
# claims & epochs
# ---------------------------------------------------------------------------


def test_claim_is_exclusive_and_returns_epoch(tmp_path):
    a = EpochLeases(tmp_path, owner="a", lease_ttl=30)
    b = EpochLeases(tmp_path, owner="b", lease_ttl=30)
    assert a.try_claim("shard0000") == 1
    assert b.try_claim("shard0000") is None  # exactly one winner
    assert b.metrics.counter("lease.contention").value >= 1
    assert a.holder("shard0000")["owner"] == "a"
    assert a.holder("shard0000")["epoch"] == 1


def test_epochs_strictly_monotonic_across_reclaim_cycles(tmp_path):
    """Every claim bumps the durable counter — the fencing token the
    (shard, epoch) WAL names depend on.  Release/reclaim/crash history
    must never reuse an epoch."""
    a = EpochLeases(tmp_path, owner="a", lease_ttl=5)
    b = EpochLeases(tmp_path, owner="b", lease_ttl=5)
    assert a.try_claim("s") == 1
    assert a.release("s")
    assert b.try_claim("s") == 2
    _age(b, "s", 60)  # b dies
    assert a.reclaim(["s"]) == ["s"]
    assert a.try_claim("s") == 3
    assert a.read_epoch("s") == 3


def test_fresh_lease_not_reclaimed(tmp_path):
    a = EpochLeases(tmp_path, owner="a", lease_ttl=30)
    b = EpochLeases(tmp_path, owner="b", lease_ttl=30)
    assert a.try_claim("s") == 1
    assert b.reclaim(["s"]) == []
    assert b.try_claim("s") is None


def test_stale_lease_reclaimed_then_claimable(tmp_path):
    a = EpochLeases(tmp_path, owner="dead", lease_ttl=5)
    b = EpochLeases(tmp_path, owner="live", lease_ttl=5)
    assert a.try_claim("s") == 1
    _age(a, "s", 60)  # heartbeats stopped long ago
    assert b.reclaim(["s"]) == ["s"]
    assert b.try_claim("s") == 2  # survivor takes over, epoch fenced up


def test_reclaim_ordering_only_expired_leases(tmp_path):
    """Clock-skew ordering: only the lease whose mtime aged past the
    TTL is reclaimable; a fresh sibling survives the same sweep."""
    a = EpochLeases(tmp_path, owner="a", lease_ttl=5)
    b = EpochLeases(tmp_path, owner="b", lease_ttl=5)
    assert a.try_claim("s0") == 1
    assert a.try_claim("s1") == 1
    _age(a, "s0", 60)  # only s0 expired
    assert b.reclaim(["s0", "s1"]) == ["s0"]
    assert b.try_claim("s0") == 2
    assert b.try_claim("s1") is None  # fresh lease survives


def test_heartbeat_defers_expiry(tmp_path):
    a = EpochLeases(tmp_path, owner="a", lease_ttl=5)
    b = EpochLeases(tmp_path, owner="b", lease_ttl=5)
    assert a.try_claim("s") == 1
    _age(a, "s", 60)
    assert a.heartbeat("s")  # mtime -> NOW: the holder is alive
    assert b.reclaim(["s"]) == []


def test_two_reclaimers_race_single_winner(tmp_path):
    """Rename-first claim-the-claim: two survivors sweeping the same
    dead lease free it exactly once, and only one subsequent claim
    wins the next epoch."""
    a = EpochLeases(tmp_path, owner="dead", lease_ttl=5)
    b = EpochLeases(tmp_path, owner="s1", lease_ttl=5)
    c = EpochLeases(tmp_path, owner="s2", lease_ttl=5)
    assert a.try_claim("s") == 1
    _age(a, "s", 60)
    freed = b.reclaim(["s"]) + c.reclaim(["s"])
    assert freed == ["s"]
    wins = [x.try_claim("s") for x in (b, c)]
    assert sorted(w for w in wins if w is not None) == [2]


# ---------------------------------------------------------------------------
# owner-verified mutation (the stalled-holder fence)
# ---------------------------------------------------------------------------


def test_heartbeat_detects_loss_and_never_refreshes_the_new_owner(tmp_path):
    """A holder that stalled past the TTL and was reclaimed must NOT
    refresh (or free) the new owner's lease — the owner+epoch check
    fences it out."""
    a = EpochLeases(tmp_path, owner="stalled", lease_ttl=5)
    b = EpochLeases(tmp_path, owner="survivor", lease_ttl=5)
    assert a.try_claim("s") == 1
    _age(a, "s", 60)
    assert b.reclaim(["s"]) == ["s"]
    assert b.try_claim("s") == 2
    _age(b, "s", 60)  # even with b's lease stale...
    assert not a.heartbeat("s")  # ...the stalled holder can't touch it
    assert not a.verify_held("s")
    assert not a.release("s")
    assert b.holder("s")["owner"] == "survivor"
    # and a no longer thinks it holds anything
    assert a.held == {}


def test_release_is_owner_verified(tmp_path):
    a = EpochLeases(tmp_path, owner="a", lease_ttl=30)
    assert a.try_claim("s") == 1
    assert a.release("s")
    assert a.holder("s") is None
    assert not a.release("s")  # idempotent: nothing held, nothing freed


def test_unleased_lists_claimable_names(tmp_path):
    a = EpochLeases(tmp_path, owner="a", lease_ttl=30)
    names = ["s0", "s1", "s2"]
    assert a.unleased(names) == names
    a.try_claim("s1")
    assert a.unleased(names) == ["s0", "s2"]


def test_torn_lease_body_is_not_a_holder(tmp_path):
    """A crash between O_EXCL create and the body write leaves an empty
    lease file: holder() answers None, verification fails, and the
    reclaim path (after TTL) frees it like any other stale lease."""
    a = EpochLeases(tmp_path, owner="a", lease_ttl=5)
    with open(a._lease_path("s"), "w"):
        pass  # empty claim, mid-crash artifact
    assert a.holder("s") is None
    assert not a.verify_held("s")
    _age(a, "s", 60)
    assert a.reclaim(["s"]) == ["s"]
    assert a.try_claim("s") == 1
