"""Core-runtime tests (parity target: hyperopt/tests/test_base.py)."""

import pickle

import numpy as np
import pytest

import jax

from hyperopt_tpu import (
    Ctrl,
    Domain,
    InvalidTrial,
    JOB_STATE_DONE,
    JOB_STATE_NEW,
    STATUS_OK,
    Trials,
    hp,
    trials_from_docs,
)
from hyperopt_tpu.base import (
    SONify,
    coarse_utcnow,
    miscs_to_idxs_vals,
    miscs_update_idxs_vals,
    spec_from_misc,
)
from hyperopt_tpu.algos import rand


def _make_doc(tid, vals, loss=None, state=JOB_STATE_NEW):
    result = {"status": STATUS_OK, "loss": loss} if loss is not None else {"status": "new"}
    return {
        "tid": tid,
        "spec": None,
        "result": result,
        "misc": {
            "tid": tid,
            "cmd": ("domain_attachment", "FMinIter_Domain"),
            "idxs": {k: [tid] for k in vals},
            "vals": {k: [v] for k, v in vals.items()},
        },
        "state": state,
        "exp_key": None,
        "owner": None,
        "version": 0,
        "book_time": None,
        "refresh_time": None,
    }


def test_sonify():
    out = SONify({"a": np.int64(3), "b": np.float32(0.5), "c": (1, 2),
                  "d": np.arange(3), "e": None, "f": True})
    assert out == {"a": 3, "b": 0.5, "c": [1, 2], "d": [0, 1, 2], "e": None, "f": True}
    assert isinstance(out["a"], int) and isinstance(out["b"], float)
    with pytest.raises(TypeError):
        SONify(object())


def test_sonify_jax_array():
    import jax.numpy as jnp

    assert SONify(jnp.asarray(2.5)) == 2.5


def test_coarse_utcnow_granularity():
    t = coarse_utcnow()
    assert t.microsecond % 1000 == 0


def test_trial_doc_validation():
    t = Trials()
    with pytest.raises(InvalidTrial):
        t.insert_trial_doc({"tid": 0})
    bad = _make_doc(0, {"x": 1.0})
    bad["state"] = 99
    with pytest.raises(InvalidTrial):
        t.insert_trial_doc(bad)
    mismatched = _make_doc(0, {"x": 1.0})
    mismatched["misc"]["tid"] = 5
    with pytest.raises(InvalidTrial):
        t.insert_trial_doc(mismatched)


def test_trials_insert_refresh_len():
    t = Trials()
    t.insert_trial_docs([_make_doc(i, {"x": float(i)}, loss=float(i),
                                   state=JOB_STATE_DONE) for i in range(5)])
    assert len(t) == 0  # not refreshed yet
    t.refresh()
    assert len(t) == 5
    assert t.tids == list(range(5))
    assert t.losses() == [0.0, 1.0, 2.0, 3.0, 4.0]
    assert t.argmin == {"x": 0.0}
    assert t.best_trial["tid"] == 0
    assert t.average_best_error() == 0.0


def test_trials_new_trial_ids_monotonic():
    t = Trials()
    a = t.new_trial_ids(3)
    b = t.new_trial_ids(2)
    assert a == [0, 1, 2]
    assert b == [3, 4]


def test_trials_exp_key_scoping():
    t = Trials(exp_key="e1")
    doc = _make_doc(0, {"x": 1.0}, loss=1.0, state=JOB_STATE_DONE)
    doc["exp_key"] = "e1"
    other = _make_doc(1, {"x": 2.0}, loss=2.0, state=JOB_STATE_DONE)
    other["exp_key"] = "e2"
    t.insert_trial_docs([doc, other])
    t.refresh()
    assert len(t) == 1
    assert t.count_by_state_unsynced(JOB_STATE_DONE) == 1


def test_trials_pickle_roundtrip():
    t = Trials()
    t.insert_trial_docs([_make_doc(i, {"x": float(i)}, loss=float(i),
                                   state=JOB_STATE_DONE) for i in range(3)])
    t.refresh()
    t2 = pickle.loads(pickle.dumps(t))
    assert len(t2) == 3
    assert t2.losses() == t.losses()
    assert t2.argmin == t.argmin
    # history rebuilds after unpickle
    h = t2.padded_history(("x",))
    assert h["n"] == 3


def test_trials_from_docs():
    docs = [_make_doc(i, {"x": float(i)}, loss=float(i), state=JOB_STATE_DONE)
            for i in range(4)]
    t = trials_from_docs(docs)
    assert len(t) == 4
    with pytest.raises(InvalidTrial):
        trials_from_docs([{"nope": 1}])


def test_miscs_round_trip():
    docs = [_make_doc(i, {"x": float(i), "y": float(-i)}) for i in range(3)]
    miscs = [d["misc"] for d in docs]
    idxs, vals = miscs_to_idxs_vals(miscs)
    assert idxs["x"] == [0, 1, 2]
    assert vals["y"] == [0.0, -1.0, -2.0]
    # wipe and write back
    for m in miscs:
        m["idxs"] = {"x": [], "y": []}
        m["vals"] = {"x": [], "y": []}
    miscs_update_idxs_vals(miscs, idxs, vals)
    idxs2, vals2 = miscs_to_idxs_vals(miscs)
    assert idxs2 == idxs and vals2 == vals


def test_spec_from_misc_skips_inactive():
    misc = {"tid": 0, "cmd": None, "idxs": {"x": [0], "y": []},
            "vals": {"x": [1.5], "y": []}}
    assert spec_from_misc(misc) == {"x": 1.5}


def test_padded_history_growth_and_masks():
    t = Trials()
    n = 140  # crosses the 128-slot capacity bucket
    docs = []
    for i in range(n):
        vals = {"x": float(i)} if i % 2 == 0 else {}
        d = _make_doc(i, vals, loss=float(i), state=JOB_STATE_DONE)
        docs.append(d)
    t.insert_trial_docs(docs)
    t.refresh()
    h = t.padded_history(("x",))
    assert h["n"] == n
    assert h["cap"] == 256
    assert h["active"]["x"].sum() == (n + 1) // 2
    assert h["has_loss"].sum() == n
    # incremental: appending more only folds the new ones
    t.insert_trial_docs([_make_doc(n, {"x": 1.0}, loss=0.5, state=JOB_STATE_DONE)])
    t.refresh()
    h2 = t.padded_history(("x",))
    assert h2["n"] == n + 1


def test_domain_evaluate_scalar_and_dict():
    d = Domain(lambda cfg: cfg["x"] ** 2, {"x": hp.uniform("x", -1, 1)})
    out = d.evaluate({"x": 2.0}, None)
    assert out == {"loss": 4.0, "status": STATUS_OK}

    d2 = Domain(lambda cfg: {"loss": cfg["x"], "status": STATUS_OK},
                {"x": hp.uniform("x", -1, 1)})
    assert d2.evaluate({"x": 0.5}, None)["loss"] == 0.5


def test_domain_invalid_results():
    from hyperopt_tpu import InvalidLoss, InvalidResultStatus

    d = Domain(lambda cfg: float("nan"), {"x": hp.uniform("x", -1, 1)})
    with pytest.raises(InvalidLoss):
        d.evaluate({"x": 0.0}, None)
    d2 = Domain(lambda cfg: {"status": "bogus"}, {"x": hp.uniform("x", -1, 1)})
    with pytest.raises(InvalidResultStatus):
        d2.evaluate({"x": 0.0}, None)
    d3 = Domain(lambda cfg: {"status": STATUS_OK}, {"x": hp.uniform("x", -1, 1)})
    with pytest.raises(InvalidLoss):
        d3.evaluate({"x": 0.0}, None)


def test_domain_pickles_without_jit_handles():
    d = Domain(None, {"x": hp.uniform("x", -1, 1)})
    d.cs.sample_flat_jit(jax.random.PRNGKey(0))  # force-compile
    d2 = pickle.loads(pickle.dumps(d))
    # usable after reload
    v = d2.cs.sample_flat_jit(jax.random.PRNGKey(0))
    assert "x" in v


def test_ctrl_inject_results():
    t = Trials()
    ctrl = Ctrl(t)
    misc = {"tid": 0, "cmd": None, "idxs": {"x": [0]}, "vals": {"x": [1.0]}}
    ctrl.inject_results([None], [{"status": STATUS_OK, "loss": 1.0}], [misc],
                        new_tids=[0])
    t.refresh()
    assert len(t) == 1
    assert t.trials[0]["state"] == JOB_STATE_DONE


def test_delete_all():
    t = Trials()
    t.insert_trial_docs([_make_doc(0, {"x": 1.0}, loss=1.0, state=JOB_STATE_DONE)])
    t.refresh()
    t.attachments["blob"] = b"x"
    t.delete_all()
    assert len(t) == 0
    assert t.attachments == {}
