"""ISSUE 18: the blackbox prober & continuous correctness audit.

The acceptance pins:

* the committed golden fixture really is what the serving path emits:
  a pinned canary driven through the REAL handler path digests to the
  fixture entry bitwise — when this fails, either the proposal stream
  regressed or an intentional algorithm change needs
  ``python -m hyperopt_tpu.obs.prober --regen-golden`` and review;
* corruption on the serving path turns the verdict red within bounded
  cycles, with an honest fake-clock detection latency, an evidence
  bundle, and ONE edge-triggered escalation per red episode;
* canary traffic is free: armed == disarmed tenant proposals
  bit-identical (directly AND over HTTP), and canary studies never
  touch the quality plane, the cost ledger, or the tenant SLOs;
* verdict ledgers are CRC-sealed and torn-tolerant, read back with the
  census discipline (corrupt counted, torn tail silent);
* the probe SLO objectives exist only when the prober is armed.
"""

import json
import os
import sys
import threading
import time

import pytest

from hyperopt_tpu import chaos, hp
from hyperopt_tpu._env import (
    parse_probe,
    parse_probe_period,
    parse_probe_slo,
)
from hyperopt_tpu.obs.prober import (
    CANARY,
    ProbeLedger,
    Prober,
    _LocalTransport,
    canary_key,
    detection_stats,
    load_golden,
    local_digest,
    probes_path_for,
    read_probes,
    stream_digest,
)
from hyperopt_tpu.obs.quality import QualityPlane
from hyperopt_tpu.obs.slo import PROBE_TARGETS, SLOPlane
from hyperopt_tpu.service import integrity
from hyperopt_tpu.service.scheduler import StudyScheduler
from hyperopt_tpu.service.server import ServiceHTTPServer

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))

SPACE = {"x": hp.uniform("x", -5, 5)}
SPACE_SPEC = {"x": {"dist": "uniform", "args": [-5, 5]}}


@pytest.fixture(autouse=True)
def _chaos_clean():
    chaos.reset()
    yield
    chaos.reset()


def _local_server():
    sched = StudyScheduler(wal=False, quality=False)
    return ServiceHTTPServer(0, scheduler=sched, trace=False, slo=False)


def _local_prober(srv, **kw):
    kw.setdefault("transport_factory",
                  lambda url: _LocalTransport(srv))
    kw.setdefault("period", 30.0)
    return Prober(["local://srv"], **kw)


# ---------------------------------------------------------------------------
# the golden fixture
# ---------------------------------------------------------------------------


def test_golden_digest_matches_committed_fixture():
    """THE regression pin: the serving path's canary stream must digest
    to the committed fixture bitwise."""
    golden = load_golden(CANARY)
    if golden is None:
        pytest.skip("no committed golden for this backend (TOFU mode)")
    digest, flagged = local_digest(CANARY)
    assert not flagged, "canary stream came back degraded/warming"
    assert digest == golden, (
        f"canary proposal stream digest {digest} != committed golden "
        f"{golden} for {canary_key(CANARY)}.  Either the proposal "
        "path regressed (find it before shipping) or an intentional "
        "algorithm change moved the stream — then regenerate and "
        "review the fixture: python -m hyperopt_tpu.obs.prober "
        "--regen-golden")


def test_local_digest_is_deterministic():
    a, _ = local_digest(CANARY)
    b, _ = local_digest(CANARY)
    assert a == b


def test_stream_digest_canonical_and_wire_stable():
    stream = [{"tid": 0, "params": {"x": 0.1 + 0.2, "y": -3.5}},
              {"tid": 1, "params": {"y": 1e-17, "x": 2.0}}]
    d1 = stream_digest(stream)
    # a JSON wire round trip must not move the digest (shortest-repr)
    d2 = stream_digest(json.loads(json.dumps(stream)))
    # key order must not matter (canonical sort)
    d3 = stream_digest([{"params": dict(reversed(list(
        e["params"].items()))), "tid": e["tid"]} for e in stream])
    assert d1 == d2 == d3
    assert d1 != stream_digest(
        [{"tid": 0, "params": {"x": 0.30000000000000010, "y": -3.5}},
         stream[1]])


def test_canary_key_pins_every_config_axis():
    base = canary_key()
    assert base == canary_key(CANARY)
    for knob, val in (("seed", 7), ("asks", 9), ("n_startup", 1),
                      ("n_ei", 8), ("zoo", "other")):
        assert canary_key({knob: val}) != base


# ---------------------------------------------------------------------------
# cycles, verdicts, detection
# ---------------------------------------------------------------------------


def test_clean_cycle_is_ok_green_and_sealed(tmp_path):
    srv = _local_server()
    led = probes_path_for(tmp_path, "r0")
    p = _local_prober(srv, ledger_path=led, replica="r0",
                      clock=lambda: 1000.0)
    s = p.run_cycle(now=1000.0)
    assert s["verdict"] == "ok" and not s["diverged"]
    assert p.green(now=1000.0)
    assert p.streak == 1
    recs, corrupt, torn = read_probes(led)
    assert corrupt == 0 and torn == 0
    assert [r["verdict"] for r in recs] == ["ok"]
    assert recs[0]["replica"] == "r0"
    assert recs[0]["canary"] == canary_key(CANARY)
    assert recs[0]["digest"]
    h = p.healthz_fields(now=1000.0)
    assert h["green"] and h["last_verdict"] == "ok"
    assert h["golden_match_streak"] == 1


def test_corruption_detected_with_fake_clock_latency(tmp_path):
    srv = _local_server()
    led = probes_path_for(tmp_path, "r0")
    p = _local_prober(srv, ledger_path=led)
    assert p.run_cycle(now=100.0)["verdict"] == "ok"
    # silent float corruption on the serving readback path: no flag, no
    # error — exactly the failure the blackbox exists to catch
    chaos.configure("7:corrupt@tick:1.0")
    s = p.run_cycle(now=107.0)
    assert s["verdict"] == "mismatch"
    assert s["detection_latency_sec"] == pytest.approx(7.0)
    assert p.streak == 0 and not p.green(now=107.0)
    # the ledger agrees: detection_stats recomputes the same latency
    recs, _, _ = read_probes(led)
    st = detection_stats(recs)
    assert st["episodes"] == 1
    assert st["mean_sec"] == pytest.approx(7.0)
    # evidence bundle written and readable
    ev = [r.get("evidence") for r in recs if r.get("evidence")]
    assert ev, "mismatch verdict carries no evidence bundle"
    with open(os.path.join(ev[-1], "bundle.json"),
              encoding="utf-8") as f:
        bundle = json.load(f)
    assert bundle["verdict"] == "mismatch"


def test_escalation_is_once_per_episode(tmp_path):
    srv = _local_server()
    p = _local_prober(srv, escalation_cooldown=0.0,
                      profile_capture=False)
    assert p.run_cycle(now=10.0)["verdict"] == "ok"
    chaos.configure("7:corrupt@tick:1.0")
    for i, now in enumerate((20.0, 30.0, 40.0)):
        assert p.run_cycle(now=now)["verdict"] == "mismatch"
    assert p.escalations == 1, "a red STREAK must escalate once"
    chaos.configure(None)
    assert p.run_cycle(now=50.0)["verdict"] == "ok"
    chaos.configure("7:corrupt@tick:1.0")
    assert p.run_cycle(now=60.0)["verdict"] != "ok"
    assert p.escalations == 2, "a new episode escalates again"


def test_error_verdict_fail_open_never_raises():
    class Boom:
        def request(self, *a, **kw):
            raise RuntimeError("probe transport exploded")

    p = Prober(["local://x"], transport_factory=lambda url: Boom(),
               period=30.0)
    s = p.run_cycle(now=5.0)
    assert s["verdict"] == "error"
    assert not p.green(now=5.0)


def test_fleet_divergence_turns_mismatch():
    """Two replicas answering different clean streams = divergence,
    even with no golden fixture (TOFU mode)."""
    srv_a, srv_b = _local_server(), _local_server()

    class Skewed(_LocalTransport):
        def request(self, method, path, body=None):
            if path == "/study" and body:
                body = dict(body, seed=int(body["seed"]) + 1)
            return super().request(method, path, body)

    transports = {"local://a": _LocalTransport(srv_a),
                  "local://b": Skewed(srv_b)}
    p = Prober(["local://a", "local://b"], period=30.0,
               transport_factory=lambda url: transports[url],
               golden=None, profile_capture=False)
    p.golden, p.golden_source = None, "tofu"  # force pure TOFU
    s = p.run_cycle(now=1.0)
    assert s["diverged"]
    assert s["verdict"] == "mismatch"


def test_tofu_pins_first_clean_digest():
    srv = _local_server()
    p = _local_prober(srv)
    p.golden, p.golden_source = None, "tofu"
    assert p.run_cycle(now=1.0)["verdict"] == "ok"
    assert p.golden is not None          # self-pinned
    pinned = p.golden
    assert p.run_cycle(now=2.0)["verdict"] == "ok"
    assert p.golden == pinned


def test_prober_thread_starts_and_stops():
    srv = _local_server()
    p = _local_prober(srv, period=0.05)
    names = lambda: {t.name for t in threading.enumerate()}  # noqa: E731
    assert "hyperopt-prober" not in names()
    p.start()
    assert "hyperopt-prober" in names()
    deadline = time.monotonic() + 10.0
    while p.cycles < 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    p.stop()
    assert "hyperopt-prober" not in names()
    assert p.cycles >= 1 and p.last["verdict"] == "ok"


# ---------------------------------------------------------------------------
# the sealed ledger
# ---------------------------------------------------------------------------


def test_ledger_corrupt_line_counted_torn_tail_silent(tmp_path):
    led = str(tmp_path / "r0.jsonl")
    L = ProbeLedger(led)
    for i in range(3):
        L.append({"kind": "probe", "cycle": i, "ts": float(i),
                  "verdict": "ok"})
    with open(led, "ab") as f:
        f.write(b'{"kind": "probe", "torn-no-newline')
    data = open(led, "rb").read()
    # sealed lines are canonical compact JSON (no spaces after ':')
    flipped = data.replace(b'"cycle":1', b'"cycle":9', 1)
    assert flipped != data
    with open(led, "wb") as f:
        f.write(flipped)
    recs, corrupt, torn = read_probes(led)
    assert corrupt == 1 and torn == 1
    assert [r["cycle"] for r in recs] == [0, 2]


def test_ledger_append_fail_open(tmp_path, caplog):
    L = ProbeLedger(str(tmp_path / "nope" / "x" / "r0.jsonl"))
    os.makedirs(os.path.dirname(os.path.dirname(L.path)))
    with open(os.path.dirname(os.path.dirname(L.path)) + "/x", "w"):
        pass  # a FILE where the dir should be → OSError on makedirs
    L.append({"kind": "probe", "verdict": "ok"})  # must not raise
    L.append({"kind": "probe", "verdict": "ok"})  # warn-once latch


def test_ledger_lines_are_integrity_sealed(tmp_path):
    led = str(tmp_path / "r0.jsonl")
    ProbeLedger(led).append({"kind": "probe", "cycle": 1,
                             "verdict": "ok"})
    line = open(led, encoding="utf-8").read().strip()
    checked = list(integrity.iter_checked_jsonl(led))
    assert len(checked) == 1 and checked[0].status == integrity.OK
    assert integrity.CHECKSUM_FIELD in json.loads(line)


# ---------------------------------------------------------------------------
# canary traffic is free
# ---------------------------------------------------------------------------


def _drive_direct(sched, sid, n):
    out = []
    for _ in range(n):
        a = sched.ask(sid)[0]
        out.append((a["tid"], repr(a["params"]["x"])))
        sched.tell(sid, a["tid"], float((a["params"]["x"] - 1.0) ** 2))
    return out


def test_armed_equals_disarmed_bit_identical_direct():
    """Tenant proposals with probe cycles interleaved == without."""
    on = StudyScheduler(wal=False, quality=False)
    srv_on = ServiceHTTPServer(0, scheduler=on, trace=False, slo=False)
    off = StudyScheduler(wal=False, quality=False)
    p = _local_prober(srv_on)

    sid_on = on.create_study(SPACE, seed=21, n_startup_jobs=2)
    sid_off = off.create_study(SPACE, seed=21, n_startup_jobs=2)
    seq_on, seq_off = [], []
    for i in range(3):
        assert p.run_cycle(now=float(i))["verdict"] == "ok"
        seq_on += _drive_direct(on, sid_on, 3)
        seq_off += _drive_direct(off, sid_off, 3)
    assert seq_on == seq_off


def test_armed_equals_disarmed_bit_identical_over_http():
    def drive(srv, sid, n):
        seq = []
        for _ in range(n):
            code, a = srv.handle("POST", "/ask", {"study_id": sid})
            assert code == 200
            t = a["trials"][0]
            seq.append((t["tid"], repr(t["params"]["x"])))
            code, _ = srv.handle("POST", "/tell", {
                "study_id": sid, "tid": t["tid"],
                "loss": float((t["params"]["x"] - 1.0) ** 2)})
            assert code == 200
        return seq

    seqs = {}
    for armed in (True, False):
        sched = StudyScheduler(wal=False, quality=False)
        srv = ServiceHTTPServer(0, scheduler=sched, trace=False,
                                slo=False)
        p = _local_prober(srv) if armed else None
        code, r = srv.handle("POST", "/study", {
            "space": SPACE_SPEC, "seed": 33, "n_startup_jobs": 2})
        assert code == 200
        sid = r["study_id"]
        seq = []
        for i in range(3):
            if p is not None:
                assert p.run_cycle(now=float(i))["verdict"] == "ok"
            seq += drive(srv, sid, 3)
        seqs[armed] = seq
    assert seqs[True] == seqs[False]


def test_canary_studies_invisible_to_quality_and_load():
    from hyperopt_tpu.obs.load import CostLedger

    sched = StudyScheduler(wal=False, quality=QualityPlane(),
                           load=CostLedger())
    canary = sched.create_study(SPACE, seed=5, n_startup_jobs=2,
                                canary=True)
    tenant = sched.create_study(SPACE, seed=6, n_startup_jobs=2)
    _drive_direct(sched, canary, 6)
    _drive_direct(sched, tenant, 6)
    # quality plane: only the tenant is tracked
    assert sched.quality.study_status(canary) is None
    assert sched.quality.study_status(tenant) is not None
    # cost ledger: the canary is never charged
    assert sched.load.study_status(canary) is None
    t = sched.load.study_status(tenant)
    assert t is not None and t["tells"] == 6


def test_canary_flag_rides_status_and_wal_replay(tmp_path):
    sched = StudyScheduler(store_root=str(tmp_path))
    sid = sched.create_study(SPACE, seed=5, n_startup_jobs=2,
                             space_spec={"space": SPACE_SPEC}, canary=True)
    _drive_direct(sched, sid, 3)
    assert sched._studies[sid].canary
    assert sched._studies[sid].status_dict().get("canary") is True
    del sched  # crash-style: no drain, resume replays the WAL
    resumed = StudyScheduler(store_root=str(tmp_path),
                             quality=QualityPlane())
    assert sid in resumed._studies, "canary study did not resume"
    assert resumed._studies[sid].canary, \
        "canary flag lost across WAL replay"
    assert resumed.quality.study_status(sid) is None


def test_probe_header_skips_tenant_slo():
    sched = StudyScheduler(wal=False, quality=False)
    srv = ServiceHTTPServer(0, scheduler=sched, trace=False, slo=True)
    before = srv.slo.status()
    code, _ = srv.handle("POST", "/study",
                         {"space": SPACE_SPEC, "seed": 1,
                          "canary": True},
                         headers={"x-probe": "1"})
    assert code == 200
    after = srv.slo.status()
    assert (after["availability"]["window_events"]
            == before["availability"]["window_events"]), \
        "probe-tagged requests leaked into the tenant SLO window"
    code, _ = srv.handle("GET", "/healthz", None)
    assert code == 200


# ---------------------------------------------------------------------------
# SLO objectives, server surfaces
# ---------------------------------------------------------------------------


def test_probe_objectives_installed_only_when_armed():
    sched = StudyScheduler(wal=False, quality=False)
    srv = ServiceHTTPServer(0, scheduler=sched, trace=False, slo=True)
    assert "probe_avail" not in srv.slo.status()
    assert srv.start()
    try:
        p = srv.arm_prober(period=30.0)
        assert p is not None
        assert srv.arm_prober() is p          # idempotent
        st = srv.slo.status()
        for name in PROBE_TARGETS:
            assert name in st
    finally:
        srv.drain()


def test_probe_slo_burns_on_mismatch():
    plane = SLOPlane(clock=lambda: 1000.0)
    for name, spec in PROBE_TARGETS.items():
        plane.add_objective(name, spec)
    srv = _local_server()
    p = _local_prober(srv, slo=plane)
    assert p.run_cycle(now=1000.0)["verdict"] == "ok"
    g0 = plane.status()["probe_golden_match"]
    assert g0["window_events"] >= 1
    assert g0["budget_remaining_frac"] == pytest.approx(1.0)
    chaos.configure("7:corrupt@tick:1.0")
    assert p.run_cycle(now=1010.0)["verdict"] == "mismatch"
    g1 = plane.status()["probe_golden_match"]
    assert g1["window_events"] == g0["window_events"] + 1
    assert g1["budget_remaining_frac"] < g0["budget_remaining_frac"], \
        "a golden mismatch must burn probe_golden_match budget"
    a1 = plane.status()["probe_avail"]
    assert a1["budget_remaining_frac"] == pytest.approx(1.0), \
        "mismatch is not an availability failure"


def test_server_surfaces_probes_and_healthz():
    sched = StudyScheduler(wal=False, quality=False)
    srv = ServiceHTTPServer(0, scheduler=sched, trace=False, slo=False)
    # disarmed: /probes answers, healthz has no probe section
    code, d = srv.handle("GET", "/probes", None)
    assert code == 200 and d["armed"] is False
    code, h = srv.handle("GET", "/healthz", None)
    assert code == 200 and "probe" not in h
    assert "probes" not in srv.snapshot_dict()
    assert srv.start()
    try:
        p = srv.arm_prober(period=30.0)
        p.run_cycle()
        code, d = srv.handle("GET", "/probes", None)
        assert code == 200 and d["armed"] is True
        assert d["cycles"] >= 1 and d["golden_match_streak"] >= 1
        code, h = srv.handle("GET", "/healthz", None)
        assert code == 200
        assert h["ok"] and h["probe"]["green"]
        snap = srv.snapshot_dict()
        assert snap["probes"]["armed"] is True
    finally:
        srv.drain()


def test_metrics_expose_probe_families():
    from validate_scrape import PROBE_FAMILIES, validate_probe_families

    sched = StudyScheduler(wal=False, quality=False)
    srv = ServiceHTTPServer(0, scheduler=sched, trace=False, slo=False)
    assert srv.start()
    try:
        p = srv.arm_prober(period=30.0)
        p.run_cycle()
        # /metrics only exists on the real HTTP dispatch path
        import urllib.request
        with urllib.request.urlopen(srv.url + "/metrics", timeout=10) as r:
            assert r.status == 200
            text = r.read().decode("utf-8")
        errors = validate_probe_families(text)
        assert errors == [], errors
        for fam in PROBE_FAMILIES:
            assert fam in text
    finally:
        srv.drain()


def test_disarmed_prober_costs_nothing():
    n0 = threading.active_count()
    sched = StudyScheduler(wal=False, quality=False)
    srv = ServiceHTTPServer(0, scheduler=sched, trace=False, slo=False)
    assert srv.prober is None
    assert threading.active_count() == n0
    code, _ = srv.handle("POST", "/study",
                         {"space": SPACE_SPEC, "seed": 1})
    assert code == 200
    assert srv.prober is None and threading.active_count() == n0


# ---------------------------------------------------------------------------
# knobs, report, restart gate
# ---------------------------------------------------------------------------


def test_env_knobs(monkeypatch):
    monkeypatch.delenv("HYPEROPT_TPU_PROBE", raising=False)
    assert parse_probe() is False             # default OFF
    monkeypatch.setenv("HYPEROPT_TPU_PROBE", "1")
    assert parse_probe() is True
    monkeypatch.setenv("HYPEROPT_TPU_PROBE_PERIOD", "2.5")
    assert parse_probe_period() == 2.5
    monkeypatch.setenv("HYPEROPT_TPU_PROBE_PERIOD", "bogus")
    assert parse_probe_period() == 30.0       # warn-once fallback
    monkeypatch.delenv("HYPEROPT_TPU_PROBE_SLO", raising=False)
    assert parse_probe_slo() == PROBE_TARGETS
    monkeypatch.setenv("HYPEROPT_TPU_PROBE_SLO", "off")
    assert parse_probe_slo() is None
    monkeypatch.setenv("HYPEROPT_TPU_PROBE_SLO",
                       "avail=99.5,ask_p99_ms=500")
    cfg = parse_probe_slo()
    assert cfg["probe_avail"]["target"] == 0.995
    assert cfg["probe_ask_p99_ms"]["threshold_ms"] == 500.0


def test_report_probes_view(tmp_path):
    from hyperopt_tpu.obs.report import main as report_main

    led = probes_path_for(tmp_path, "r1")
    L = ProbeLedger(led)
    L.append({"kind": "probe", "cycle": 1, "ts": 10.0, "verdict": "ok",
              "replica": "r1", "target": "u", "golden": "abc",
              "golden_source": "fixture", "canary": canary_key(),
              "backend": "cpu"})
    L.append({"kind": "probe", "cycle": 2, "ts": 14.0,
              "verdict": "mismatch", "why": "digest drift",
              "replica": "r1", "target": "u", "golden": "abc",
              "golden_source": "fixture", "canary": canary_key(),
              "backend": "cpu"})
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = report_main(["--probes", str(tmp_path)])
    assert rc == 0
    text = buf.getvalue()
    assert "blackbox probes" in text
    assert "mismatch" in text and "4.00s" in text


def test_blackbox_green_gate(monkeypatch):
    import fleet_restart

    answers = {}
    monkeypatch.setattr(fleet_restart, "fetch_healthz",
                        lambda url, timeout=3.0: answers.get(url))
    # all disarmed: green (the gate never manufactures a signal)
    answers["a"] = {"ok": True}
    answers["b"] = {"ok": True}
    assert fleet_restart.blackbox_green(["a", "b"])
    # an armed red replica vetoes
    answers["b"] = {"ok": True, "probe": {"green": False,
                                          "last_verdict": "mismatch"}}
    assert not fleet_restart.blackbox_green(["a", "b"])
    # armed green passes; a dead replica vetoes
    answers["b"] = {"ok": True, "probe": {"green": True}}
    assert fleet_restart.blackbox_green(["a", "b"])
    answers["a"] = None
    assert not fleet_restart.blackbox_green(["a", "b"])


def test_prober_cli_runs_bounded_cycles(tmp_path):
    """The standalone entry point: N cycles against a live HTTP
    replica, sealed ledger on disk, exit code reflects the verdict."""
    from hyperopt_tpu.obs.prober import main as prober_main

    sched = StudyScheduler(wal=False, quality=False)
    srv = ServiceHTTPServer(0, scheduler=sched, trace=False, slo=False)
    assert srv.start()
    led = str(tmp_path / "cli.jsonl")
    try:
        rc = prober_main(["--targets", srv.url, "--cycles", "1",
                          "--period", "1.0", "--ledger", led,
                          "--replica", "cli"])
        assert rc == 0
        recs, corrupt, _ = read_probes(led)
        assert corrupt == 0
        assert [r["verdict"] for r in recs] == ["ok"]
    finally:
        srv.drain()
