"""Elastic-fleet driver tests (ISSUE 8 tentpole).

Doctrine (SURVEY.md §4): "distributed" is tested as REAL local processes.
The crash test SIGKILLs an actual ``fmin_multihost(fleet_dir=...)``
controller subprocess mid-generation and resumes the store with a fleet of
a DIFFERENT size, which must reach a bitwise-identical history — the
re-bucketing invariant plus lease reclaim, end to end.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from hyperopt_tpu.exceptions import FleetDegraded
from hyperopt_tpu.parallel.driver import _timed_gather, fmin_multihost
from hyperopt_tpu.obs import RunObs
from hyperopt_tpu.zoo import ZOO

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "_fleet_child.py")

DOM = ZOO["branin"]


def _obj(d):
    return float(DOM.objective(d))


def _child_env():
    from hyperopt_tpu._env import forced_cpu_env

    env = forced_cpu_env(dict(os.environ), n_devices=1)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("HYPEROPT_TPU_CHAOS", None)
    return env


# ---------------------------------------------------------------------------
# bitwise parity with the collective driver
# ---------------------------------------------------------------------------


def test_fleet_single_controller_matches_collective_bitwise(tmp_path):
    ref = fmin_multihost(_obj, DOM.space, max_evals=24, batch=8, seed=3,
                         _force_single=True)
    r = fmin_multihost(_obj, DOM.space, max_evals=24, batch=8, seed=3,
                       fleet_dir=str(tmp_path / "f"), n_shards=4)
    assert r.checksum == ref.checksum
    assert r.best_loss == ref.best_loss
    np.testing.assert_array_equal(r.losses, ref.losses)
    for l in r.vals:
        np.testing.assert_array_equal(r.vals[l], ref.vals[l])


def test_fleet_partial_final_generation(tmp_path):
    # max_evals not a multiple of batch: the short generation occupies only
    # the first B shards and still folds bitwise vs the collective driver
    ref = fmin_multihost(_obj, DOM.space, max_evals=20, batch=8, seed=3,
                         _force_single=True)
    r = fmin_multihost(_obj, DOM.space, max_evals=20, batch=8, seed=3,
                       fleet_dir=str(tmp_path / "f"), n_shards=4)
    assert r.n_evals == 20
    assert r.checksum == ref.checksum


def test_fleet_store_replay_and_extension_bitwise(tmp_path):
    ref = fmin_multihost(_obj, DOM.space, max_evals=48, batch=8, seed=3,
                         _force_single=True)
    fdir = str(tmp_path / "f")
    fmin_multihost(_obj, DOM.space, max_evals=24, batch=8, seed=3,
                   fleet_dir=fdir, n_shards=4)
    # the store IS the checkpoint: a later (restarted) controller replays
    # the 3 published generations without re-evaluating, then evaluates on
    r = fmin_multihost(_obj, DOM.space, max_evals=48, batch=8, seed=3,
                       fleet_dir=fdir, n_shards=4)
    assert r.checksum == ref.checksum
    np.testing.assert_array_equal(r.losses, ref.losses)


def test_fleet_params_pinned_write_once(tmp_path):
    fdir = str(tmp_path / "f")
    fmin_multihost(_obj, DOM.space, max_evals=8, batch=8, seed=3,
                   fleet_dir=fdir, n_shards=4)
    with pytest.raises(ValueError, match="identical params"):
        fmin_multihost(_obj, DOM.space, max_evals=8, batch=8, seed=4,
                       fleet_dir=fdir, n_shards=4)
    with pytest.raises(ValueError, match="identical params"):
        # n_shards is part of the pinned re-bucketing structure
        fmin_multihost(_obj, DOM.space, max_evals=8, batch=8, seed=3,
                       fleet_dir=fdir, n_shards=2)


def test_fleet_divergence_checksum_detected(tmp_path):
    from hyperopt_tpu.parallel.driver import ControllerDivergence
    from hyperopt_tpu.parallel.membership import FleetMembership

    fdir = str(tmp_path / "f")
    evil = FleetMembership(fdir, owner="evil")
    evil.write_checksum(0, "deadbeef")  # a controller that folded garbage
    with pytest.raises(ControllerDivergence):
        fmin_multihost(_obj, DOM.space, max_evals=8, batch=8, seed=3,
                       fleet_dir=fdir, n_shards=4)


def test_fleet_failed_trials_fold_bitwise(tmp_path):
    # the failure must be DETERMINISTIC IN THE SAMPLE (the fleet contract:
    # shards evaluate in lease order, not global call order — an objective
    # keyed on call count would fail different trials per topology, which
    # is exactly the nondeterminism the divergence checksum exists to
    # catch)
    def flaky(d):
        if (float(d["x"]) * 10) % 1 < 0.2:  # ~20% of samples, value-keyed
            raise RuntimeError("flaky trial")
        return _obj(d)

    ref = fmin_multihost(flaky, DOM.space, max_evals=24, batch=8, seed=0,
                         _force_single=True)
    assert np.isinf(ref.losses).any()  # some trials really failed
    r = fmin_multihost(flaky, DOM.space, max_evals=24, batch=8, seed=0,
                       fleet_dir=str(tmp_path / "f"), n_shards=4)
    assert r.checksum == ref.checksum  # NaN raw losses digest identically


# ---------------------------------------------------------------------------
# degrade-to-shrink: the collective timeout path
# ---------------------------------------------------------------------------


def test_timed_gather_passthrough_and_errors():
    obs = RunObs()
    assert _timed_gather(lambda: 42, None, "x", obs, lambda: None) == 42
    assert _timed_gather(lambda: 42, 5.0, "x", obs, lambda: None) == 42
    with pytest.raises(RuntimeError, match="boom"):
        _timed_gather(_raise, 5.0, "x", obs, lambda: None)


def _raise():
    raise RuntimeError("boom")


def test_timed_gather_degrades_to_checkpoint_and_shrink():
    obs = RunObs()
    saved = {"n": 0}

    def hung_collective():
        time.sleep(60)  # the peer never arrives

    def on_timeout():
        saved["n"] += 1  # the driver passes _save_checkpoint(force=True)
        return True      # ...which reports whether a snapshot was written

    t0 = time.monotonic()
    with pytest.raises(FleetDegraded, match="restart the surviving fleet"):
        _timed_gather(hung_collective, 0.2, "results", obs, on_timeout)
    assert time.monotonic() - t0 < 5.0  # degraded, did not hang
    assert saved["n"] == 1
    assert obs.metrics.counter("allgather.timeouts").value == 1
    # without a written checkpoint the message must NOT promise one
    with pytest.raises(FleetDegraded, match="NO checkpoint was written"):
        _timed_gather(hung_collective, 0.2, "results", obs, lambda: False)


def test_fleet_barrier_rearms_while_lease_heartbeats(tmp_path):
    # the barrier deadline measures LIVENESS, not generation wall time: a
    # missing shard whose lease mtime keeps advancing (a live holder deep
    # in a long objective) must hold the barrier open well past
    # barrier_timeout; once the heartbeats FREEZE, the barrier degrades
    # within ~barrier_timeout
    import threading

    from hyperopt_tpu.parallel.fleet import fleet_fmin
    from hyperopt_tpu.parallel.membership import FleetMembership

    fdir = str(tmp_path / "f")
    holder = FleetMembership(fdir, owner="holder", lease_ttl=1000.0)
    assert holder.try_claim(0, 0)  # shard 0 of gen 0, never published

    barrier_timeout = 0.8
    marks = {"t_barrier": None, "t_stop": None}
    stop = threading.Event()

    def beat():
        # wait until the fleet has published every OTHER shard (it is now
        # blocked on ours), then heartbeat through 3x the barrier budget
        while not stop.is_set():
            if holder.missing_shards(0, 4) == [0]:
                break
            time.sleep(0.05)
        marks["t_barrier"] = time.monotonic()
        end = time.monotonic() + 3 * barrier_timeout
        while time.monotonic() < end and not stop.is_set():
            holder.heartbeat_shard(0, 0)
            time.sleep(0.1)
        marks["t_stop"] = time.monotonic()

    th = threading.Thread(target=beat, daemon=True)
    th.start()
    try:
        with pytest.raises(FleetDegraded, match="incomplete after"):
            fleet_fmin(_obj, DOM.space, max_evals=8, fleet_dir=fdir,
                       batch=8, seed=3, n_shards=4, lease_ttl=1000.0,
                       poll_interval=0.02, barrier_timeout=barrier_timeout)
    finally:
        stop.set()
        th.join(timeout=10)
    t_raise = time.monotonic()
    assert marks["t_barrier"] is not None
    # held open across the heartbeat window (a frozen deadline would have
    # degraded ~barrier_timeout after the barrier was reached)
    assert t_raise - marks["t_barrier"] >= 2 * barrier_timeout
    # and degraded promptly once liveness froze
    assert marks["t_stop"] is not None


# ---------------------------------------------------------------------------
# crash-resume at a different fleet size (real processes, real SIGKILL)
# ---------------------------------------------------------------------------


def test_fleet_sigkill_mid_generation_resume_different_size(tmp_path):
    ref = fmin_multihost(_obj, DOM.space, max_evals=48, batch=8, seed=0,
                         _force_single=True)
    fdir = str(tmp_path / "f")
    args = [sys.executable, CHILD, fdir, "--seed", "0", "--max-evals", "48",
            "--batch", "8", "--n-shards", "4", "--lease-ttl", "1.5"]

    # leg 1: ONE controller, SIGKILLed mid-generation (after the 12th
    # objective call = inside generation 1, holding a shard lease and
    # having published part of the generation)
    p = subprocess.Popen(args + ["--echo-evals"], env=_child_env(),
                         cwd=REPO, stdout=subprocess.PIPE,
                         stderr=subprocess.DEVNULL, text=True)
    evals = 0
    deadline = time.monotonic() + 240
    while time.monotonic() < deadline:
        line = p.stdout.readline()
        if not line:
            break
        if line.startswith("EVAL"):
            evals += 1
            if evals >= 12:
                break
    assert evals >= 12, f"child produced only {evals} evals"
    os.kill(p.pid, signal.SIGKILL)
    p.wait(timeout=60)
    assert p.returncode == -signal.SIGKILL

    # leg 2: a DIFFERENTLY-SIZED fleet (two controllers) adopts the store:
    # replays published shards, reclaims the dead controller's stale
    # lease, evaluates the rest — and must land on the reference bitwise
    procs = [subprocess.Popen(args, env=_child_env(), cwd=REPO,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True)
             for _ in range(2)]
    sums = []
    for q in procs:
        out, err = q.communicate(timeout=300)
        assert q.returncode == 0, f"resume child rc={q.returncode}\n{err[-3000:]}"
        assert "FLEET_OK" in out, out
        sums.append([tok.split("=", 1)[1] for tok in out.split()
                     if tok.startswith("checksum=")][0])
    assert sums == [ref.checksum] * 2, (sums, ref.checksum)
