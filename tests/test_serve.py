"""Live observability plane (hyperopt_tpu/obs/{serve,top,devmem}.py):
scrape server, terminal dashboard, device-memory telemetry.

All tier-1 (CPU, fast).  The load-bearing invariants pinned here:

* the DISARMED hot path is untouched — no server/devmem envs means no new
  threads and TPE proposals bit-identical to an armed run's;
* ``/metrics`` is lint-clean Prometheus exposition (tiny parser in
  scripts/validate_scrape.py) with monotone counters across scrapes;
* the SSE subscriber ring drops-oldest on overflow, never blocks;
* the server fails OPEN on port collision;
* ``obs.report --format json`` and ``/snapshot`` share one serializer
  (golden-pinned structure);
* an OOM (faked ``RESOURCE_EXHAUSTED``) dump carries the devmem tail +
  live-array census — the memory narrative.
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu._env import parse_devmem_period, parse_obs_http
from hyperopt_tpu.algos import tpe
from hyperopt_tpu.obs import ObsConfig, RunObs, read_jsonl
from hyperopt_tpu.obs.devmem import (DevMemSampler, live_array_census,
                                     memory_stats, register_owner)
from hyperopt_tpu.obs.flight import FlightRecorder
from hyperopt_tpu.obs.report import (headline_sections, json_report,
                                     main as report_main,
                                     render_postmortem)
from hyperopt_tpu.obs.serve import Broadcast, ObsHTTPServer, prometheus_text
from hyperopt_tpu.obs import top as top_mod

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))
import validate_scrape  # noqa: E402  (scripts/validate_scrape.py)

SPACE = {"x": hp.uniform("x", -5, 5), "y": hp.uniform("y", 0, 3)}


def quad(d):
    return (d["x"] - 1.0) ** 2 + d["y"]


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


# ---------------------------------------------------------------------------
# env parsing (warn-and-disable, never raise)
# ---------------------------------------------------------------------------


def test_env_parsing_good_values():
    assert parse_obs_http({"HYPEROPT_TPU_OBS_HTTP": "9109"}) == 9109
    assert parse_obs_http({}) is None
    assert parse_obs_http({"HYPEROPT_TPU_OBS_HTTP": "0"}) is None
    assert parse_obs_http({"HYPEROPT_TPU_OBS_HTTP": "off"}) is None
    assert parse_devmem_period({"HYPEROPT_TPU_DEVMEM": "2.5"}) == 2.5
    assert parse_devmem_period({"HYPEROPT_TPU_DEVMEM": "on"}) == 10.0
    assert parse_devmem_period({}) is None
    assert parse_devmem_period({"HYPEROPT_TPU_DEVMEM": "off"}) is None


def test_env_parsing_bad_values_warn_and_disable(caplog):
    import logging

    with caplog.at_level(logging.WARNING, logger="hyperopt_tpu._env"):
        assert parse_obs_http({"HYPEROPT_TPU_OBS_HTTP": "not-a-port"}) is None
        assert parse_obs_http({"HYPEROPT_TPU_OBS_HTTP": "99999"}) is None
        assert parse_devmem_period({"HYPEROPT_TPU_DEVMEM": "-3"}) is None
        assert parse_devmem_period({"HYPEROPT_TPU_DEVMEM": "soon"}) is None
    assert "warn-and-disable" in caplog.text
    # config construction through the same parsers never raises either
    cfg = ObsConfig.from_env({"HYPEROPT_TPU_OBS_HTTP": "junk",
                              "HYPEROPT_TPU_DEVMEM": "junk"})
    assert cfg.http_port is None and cfg.devmem_period is None


# ---------------------------------------------------------------------------
# Prometheus exposition: lint, escaping, monotone counters
# ---------------------------------------------------------------------------


def test_prometheus_text_lints_clean():
    obs = RunObs(ObsConfig(level="basic"), run_id="serve-lint")
    obs.counter("trials.completed").inc(3)
    obs.gauge("queue_depth").set(2)
    h = obs.histogram("ask.blocked_sec")
    for v in (0.01, 0.02, 0.5):
        h.observe(v)
    text = prometheus_text(namespaces=["serve-lint"])
    assert validate_scrape.validate_metrics_text(text) == []
    samples = validate_scrape.parse_samples(text)
    assert samples[("hyperopt_tpu_trials_completed_total",
                    'namespace="serve-lint"')] == 3.0
    assert samples[("hyperopt_tpu_queue_depth",
                    'namespace="serve-lint"')] == 2.0
    # summaries expose quantiles + _sum/_count
    assert ("hyperopt_tpu_ask_blocked_sec_count",
            'namespace="serve-lint"') in samples
    assert any('quantile="0.5"' in labels for _, labels in samples)
    obs.finish()


def test_prometheus_label_escaping_and_name_sanitization():
    weird = 'run "7"\nwith\\escapes'
    obs = RunObs(ObsConfig(level="basic"), run_id=weird)
    obs.counter("devmem.samples").inc()
    text = prometheus_text(namespaces=[weird])
    assert validate_scrape.validate_metrics_text(text) == []
    assert '\\"7\\"' in text and "\\n" in text and "\\\\" in text
    # dots sanitize to underscores; every name is legal
    assert "hyperopt_tpu_devmem_samples_total" in text
    obs.finish()


def test_prometheus_counters_monotone_across_scrapes():
    obs = RunObs(ObsConfig(level="basic"), run_id="serve-mono")
    c = obs.counter("suggest.calls")
    c.inc(5)
    s1 = validate_scrape.parse_samples(
        prometheus_text(namespaces=["serve-mono"]))
    c.inc(2)
    s2 = validate_scrape.parse_samples(
        prometheus_text(namespaces=["serve-mono"]))
    series = ("hyperopt_tpu_suggest_calls_total", 'namespace="serve-mono"')
    assert s1[series] == 5.0 and s2[series] == 7.0
    obs.finish()


# ---------------------------------------------------------------------------
# SSE broadcast hub: bounded rings, drop-oldest, never block
# ---------------------------------------------------------------------------


def test_broadcast_overflow_drops_oldest_never_blocks():
    hub = Broadcast()
    sub = hub.subscribe(maxlen=8)
    t0 = time.perf_counter()
    for i in range(1000):
        hub.publish({"i": i})
    assert time.perf_counter() - t0 < 1.0  # publish never waits on readers
    recs, dropped = hub.drain(sub, timeout=0)
    assert [r["i"] for r in recs] == list(range(992, 1000))  # newest kept
    assert dropped == 992
    # a fresh publish after the drain is delivered (ring re-arms)
    hub.publish({"i": "next"})
    recs, dropped = hub.drain(sub, timeout=0)
    assert dropped == 0 and [r["i"] for r in recs] == ["next"]
    hub.unsubscribe(sub)
    assert hub.n_subscribers == 0


def test_broadcast_publish_without_subscribers_is_noop():
    hub = Broadcast()
    for i in range(100):
        hub.publish({"i": i})  # must not raise or accumulate


# ---------------------------------------------------------------------------
# fail-open server lifecycle
# ---------------------------------------------------------------------------


def test_port_collision_fails_open(caplog):
    import logging

    blocker = socket.socket()
    blocker.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    port = blocker.getsockname()[1]
    try:
        with caplog.at_level(logging.WARNING,
                             logger="hyperopt_tpu.obs.serve"):
            srv = ObsHTTPServer(port)
            assert srv.start() is False
        assert "cannot bind" in caplog.text
        assert srv.url is None
        srv.stop()  # idempotent even when never started
        # a whole RunObs armed on the occupied port still constructs fine
        obs = RunObs(ObsConfig(level="basic", http_port=port),
                     run_id="serve-collide")
        assert obs.http is None
        obs.finish()
    finally:
        blocker.close()


def test_out_of_range_port_and_hostport_forms_fail_open():
    # port past 65535 (e.g. a multihost base-port offset): OverflowError
    # from bind must degrade to warn-and-disable, never raise
    srv = ObsHTTPServer(65536)
    assert srv.start() is False
    # unparseable kwarg value: same fail-open path
    srv = ObsHTTPServer("junk")
    assert srv.start() is False
    # host:port form binds the named host
    srv = ObsHTTPServer("127.0.0.1:0")
    assert srv.start() is True
    assert srv.url.startswith("http://127.0.0.1:")
    srv.stop()
    # env parser accepts host:port and keeps the host
    assert (parse_obs_http({"HYPEROPT_TPU_OBS_HTTP": "0.0.0.0:9109"})
            == "0.0.0.0:9109")
    assert parse_obs_http({"HYPEROPT_TPU_OBS_HTTP": "0.0.0.0:junk"}) is None
    # the driver's per-controller offset keeps the host too
    from hyperopt_tpu.parallel.driver import _controller_port

    assert _controller_port("0.0.0.0:9109", 2) == "0.0.0.0:9111"
    assert _controller_port(9109, 2) == 9111
    assert _controller_port(0, 3) == 0


def test_server_serves_and_stops_cleanly():
    obs = RunObs(ObsConfig(level="basic", http_port=0), run_id="serve-live")
    assert obs.http is not None
    url = obs.http.url
    obs.counter("trials.completed").inc(4)
    obs.gauge("best_loss").set(0.25)
    text = _get(url + "/metrics")
    assert validate_scrape.validate_metrics_text(text) == []
    snap = json.loads(_get(url + "/snapshot"))
    assert validate_scrape.validate_snapshot(snap) == []
    assert snap["run_id"] == "serve-live"
    assert snap["best_loss"] == 0.25
    assert snap["trials_completed"] == 4
    assert _get(url + "/").startswith("hyperopt_tpu obs")
    obs.finish()
    # the listener is gone after finish()
    with pytest.raises(Exception):
        _get(url + "/metrics", timeout=1)


def test_server_closes_on_flight_shutdown_path():
    """The fatal-signal path (flight recorder shutdown hooks) closes a
    live listener, and the hook unregisters once the server stops."""
    from hyperopt_tpu.obs import get_flight

    fr = get_flight()
    obs = RunObs(ObsConfig(level="basic", http_port=0), run_id="serve-sig")
    url = obs.http.url
    stop_hook = obs.http.stop
    assert stop_hook in fr._shutdown_hooks
    fr.run_shutdown_hooks()  # what _signal_handler / atexit invoke
    with pytest.raises(Exception):
        _get(url + "/metrics", timeout=1)
    assert stop_hook not in fr._shutdown_hooks
    obs.finish()  # idempotent on an already-stopped server


def test_sse_events_stream_tails_spans():
    obs = RunObs(ObsConfig(level="basic", http_port=0), run_id="serve-sse")
    url = obs.http.url
    got = {}

    def reader():
        req = urllib.request.urlopen(url + "/events", timeout=10)
        buf = []
        deadline = time.time() + 10
        while time.time() < deadline:
            line = req.readline().decode()
            if line.startswith("data: "):
                buf.append(json.loads(line[len("data: "):]))
                if any(r.get("name") == "marker_event" for r in buf):
                    break
        got["records"] = buf
        req.close()

    th = threading.Thread(target=reader, daemon=True)
    th.start()
    time.sleep(0.3)  # let the client subscribe before publishing
    obs.event("marker_event", payload=1)
    th.join(timeout=15)
    assert any(r.get("name") == "marker_event"
               for r in got.get("records", [])), got
    obs.finish()


# ---------------------------------------------------------------------------
# disarmed hot path untouched
# ---------------------------------------------------------------------------


def _tpe_run(seed=11, max_evals=10, **kw):
    t = Trials()
    fmin(quad, SPACE, algo=tpe.suggest, max_evals=max_evals, trials=t,
         rstate=np.random.default_rng(seed), show_progressbar=False, **kw)
    return t


def test_disarmed_run_starts_no_new_threads_and_proposals_identical():
    t_plain = _tpe_run()
    before = {th.name for th in threading.enumerate()}
    t_again = _tpe_run()
    after = {th.name for th in threading.enumerate()}
    # no server/devmem thread appears on a disarmed run
    assert not {n for n in after - before
                if "obs-http" in n or "obs-devmem" in n}
    # armed (server + devmem) proposals are bit-identical to disarmed
    obs = ObsConfig(level="basic", http_port=0, devmem_period=30.0)
    t_armed = _tpe_run(obs=obs)
    assert t_plain.losses() == t_again.losses() == t_armed.losses()
    for a, b in zip(t_plain.trials, t_armed.trials):
        assert a["misc"]["vals"] == b["misc"]["vals"]


# ---------------------------------------------------------------------------
# shared serializer: /snapshot == report --format json (golden-pinned)
# ---------------------------------------------------------------------------

_GOLDEN_SECTIONS = {
    "ask_pipeline": {
        "blocked_sec": None,
        "calls": 4,
        "inflight": 1.0,
        "queue_depth": 0,
        "speculative": 2,
    },
    "health": {
        "asks": 2,
        "dup_rate": None,
        "ei_p50": None,
        "last_dup_rate": 0.25,
        "last_ei_p50": 0.5,
        "n_above": None,
        "n_below": None,
        "prior_fallbacks": 0,
        "proposals": 8,
    },
    "report": {
        "evaluate": {"count": 4, "frac": 0.75, "sec": 3.0},
        "suggest": {"count": 4, "frac": 0.25, "sec": 1.0},
    },
    "utilization": {
        "chunk": {
            "achieved_flops_per_sec": 500.0,
            "arithmetic_intensity": 12.5,
            "bytes_per_dispatch": 8.0,
            "dispatches": 2,
            "execute_sec_total": 0.4,
            "flops_per_dispatch": 100.0,
        },
    },
    # kernel attribution (ISSUE 7): static cost × measured execute spans,
    # plus the program's share of the suggest phase wall clock
    "roofline": {
        "chunk": {
            "achieved_flops_per_sec": 500.0,
            "arithmetic_intensity": 12.5,
            "bytes_per_dispatch": 8.0,
            "dispatches": 2,
            "execute_sec_total": 0.4,
            "flops_per_dispatch": 100.0,
            "pct_of_ask": 0.4,
        },
    },
}


def _golden_inputs():
    phases = {"suggest": {"sec": 1.0, "count": 4},
              "evaluate": {"sec": 3.0, "count": 4}}
    metrics = {"suggest.calls": 4, "suggest.speculative": 2,
               "suggest.inflight": 1.0, "queue_depth": 0,
               "health.asks": 2, "health.proposals": 8,
               "health.last_ei_p50": 0.5, "health.last_dup_rate": 0.25}
    device = {"chunk.flops": 100.0, "chunk.bytes": 8.0,
              "chunk.execute_sec": {"count": 2, "sum": 0.4}}
    return phases, metrics, device


def test_headline_sections_golden():
    phases, metrics, device = _golden_inputs()
    got = headline_sections(phases, metrics, device)
    assert got == _GOLDEN_SECTIONS


def test_snapshot_and_format_json_share_serializer(tmp_path):
    """A real armed run: the /snapshot sections and `obs.report --format
    json` sections agree on everything a finished stream can know."""
    path = str(tmp_path / "run.jsonl")
    obs = RunObs(ObsConfig(level="trace", jsonl_path=path, http_port=0),
                 run_id="serve-share")
    t = Trials()
    fmin(quad, SPACE, algo=tpe.suggest, max_evals=8, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False, obs=obs)
    # fmin finished the bundle (server stopped); rebuild sections offline
    offline = json_report([("run.jsonl", read_jsonl(path))])
    # live equivalent, re-derived from the SAME bundle's registries (the
    # registry was released on finish; the bundle keeps its object)
    phases = {k: {"sec": v["sec"], "count": v["count"]}
              for k, v in obs.tracer.totals.items()}
    from hyperopt_tpu.obs.metrics import get_metrics

    live = headline_sections(phases,
                             obs.metrics.snapshot()["metrics"],
                             get_metrics("device").snapshot()["metrics"])
    off = offline["sections"]
    assert off["ask_pipeline"] == live["ask_pipeline"]
    assert off["health"] == live["health"]
    assert set(off["report"]) == set(live["report"])
    for name, e in off["report"].items():
        assert e["count"] == live["report"][name]["count"]
        assert e["sec"] == pytest.approx(live["report"][name]["sec"],
                                         rel=1e-6)


def test_report_format_json_cli(tmp_path, capsys):
    path = str(tmp_path / "run.jsonl")
    t = Trials()
    fmin(quad, SPACE, algo=tpe.suggest, max_evals=6, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False, obs=path)
    assert report_main(["--format", "json", path]) == 0
    out = json.loads(capsys.readouterr().out)
    for section in ("report", "health", "utilization", "ask_pipeline"):
        assert section in out["sections"]
    assert out["sections"]["ask_pipeline"]["calls"] >= 6
    # --format json + --postmortem is rejected loudly
    assert report_main(["--format", "json", "--postmortem", path]) == 2


# ---------------------------------------------------------------------------
# devmem: CPU memory_stats-None path, gauges, census, OOM narrative
# ---------------------------------------------------------------------------


def test_memory_stats_guarded_on_cpu():
    stats = memory_stats()
    assert stats, "at least one device"
    for entry in stats:
        assert set(entry) == {"device", "platform", "bytes_in_use",
                              "peak_bytes_in_use", "bytes_limit"}
        # CPU backends may report None everywhere — that must be legal
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            assert entry[key] is None or isinstance(entry[key], int)


def test_devmem_sampler_gauges_and_census(tmp_path):
    import jax.numpy as jnp

    register_owner("history", (4096,))
    keepalive = jnp.zeros(4096, jnp.float32)  # a census-visible buffer
    path = str(tmp_path / "run.jsonl")
    obs = RunObs(ObsConfig(level="trace", jsonl_path=path,
                           devmem_period=0.0), run_id="serve-devmem")
    assert obs.devmem is not None
    rec = obs.devmem.sample(reason="test")
    assert rec["kind"] == "devmem" and rec["run_id"] == "serve-devmem"
    census = rec["census"]
    assert census["history"]["count"] >= 1
    assert census["history"]["bytes"] >= keepalive.nbytes
    m = obs.metrics.snapshot()["metrics"]
    assert m["devmem.samples"] >= 1
    assert m["devmem.history_bytes"] >= keepalive.nbytes
    assert m["devmem.live_arrays"] >= 1
    # the armed stream carries the record too
    obs.finish()
    recs = [r for r in read_jsonl(path) if r["kind"] == "devmem"]
    assert recs and recs[-1]["reason"] == "finish"
    del keepalive


def test_devmem_rate_limited_on_span_boundaries():
    obs = RunObs(ObsConfig(level="basic", devmem_period=3600.0),
                 run_id="serve-ratelimit")
    obs.devmem.maybe_sample()
    n1 = obs.metrics.snapshot()["metrics"]["devmem.samples"]
    for _ in range(50):
        obs.devmem_sample()  # all inside the period: no extra samples
    n2 = obs.metrics.snapshot()["metrics"]["devmem.samples"]
    assert n1 == n2 == 1
    obs.finish()


def test_oom_dump_attaches_devmem_tail_and_census(tmp_path):
    """A faked RESOURCE_EXHAUSTED through the flight excepthook leaves a
    dump with the devmem tail + an at-death census — the memory
    narrative — and the postmortem renders it."""
    fr = FlightRecorder()
    obs = RunObs(ObsConfig(level="basic", devmem_period=0.0),
                 run_id="serve-oom")
    for _ in range(3):
        obs.devmem.sample(reason="ramp")
    fr.devmem = obs.devmem
    target = str(tmp_path / "oom.flight.jsonl")
    fr.add_target(target)

    class FakeOOM(RuntimeError):
        pass

    err = FakeOOM("RESOURCE_EXHAUSTED: Out of memory allocating 2147483648 "
                  "bytes (HBM)")
    # call the hook directly (installing the real excepthook would eat the
    # test runner's); chain target is captured to keep stderr clean
    fr._prev_excepthook = lambda *a: None
    fr._excepthook(FakeOOM, err, None)

    recs = read_jsonl(target)
    kinds = {r["kind"] for r in recs}
    assert "flight_dump" in kinds
    devmem_recs = [r for r in recs if r["kind"] == "devmem"]
    assert len(devmem_recs) >= 3  # the ramp tail rode the dump
    # the excepthook took one FRESH sample at OOM time
    assert any(r.get("reason") == "oom" for r in devmem_recs)
    assert any(r["kind"] == "devmem_census" for r in recs)
    out = render_postmortem(recs, name="oom.flight.jsonl")
    assert "device memory (HBM)" in out
    assert "at-death census" in out
    obs.finish()


# ---------------------------------------------------------------------------
# real-subprocess scrape of a running fmin
# ---------------------------------------------------------------------------


def test_subprocess_scrape_of_running_fmin(tmp_path):
    child = os.path.join(os.path.dirname(__file__), "_serve_child.py")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    url_file = str(tmp_path / "url")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = (repo_root + os.pathsep
                         + os.environ.get("PYTHONPATH", ""))
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.Popen([sys.executable, child, url_file], env=env,
                            cwd=repo_root, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    try:
        deadline = time.time() + 120
        while not os.path.exists(url_file):
            assert proc.poll() is None, proc.communicate()[1][-2000:]
            assert time.time() < deadline, "child never served"
            time.sleep(0.05)
        with open(url_file) as f:
            url = f.read().strip()
        assert url.startswith("http://"), url
        # wait until the first trial landed (the url is written DURING the
        # first evaluation, before any counter increments)
        while True:
            snap = json.loads(_get(url + "/snapshot"))
            if snap.get("trials_completed", 0) >= 1:
                break
            assert time.time() < deadline, "no trial ever completed"
            time.sleep(0.05)
        assert validate_scrape.validate_snapshot(snap) == []
        text1 = _get(url + "/metrics")
        assert validate_scrape.validate_metrics_text(text1) == []
        time.sleep(0.4)
        s1 = validate_scrape.parse_samples(text1)
        s2 = validate_scrape.parse_samples(_get(url + "/metrics"))
        completed = ("hyperopt_tpu_trials_completed_total",
                     'namespace="run-1"')
        assert s2[completed] > s1[completed]  # genuinely mid-run
        out, err = proc.communicate(timeout=120)
        assert "CHILD_DONE" in out, err[-2000:]
    finally:
        if proc.poll() is None:
            proc.kill()


# ---------------------------------------------------------------------------
# obs.top: frame rendering (URL-shaped and file-shaped sources)
# ---------------------------------------------------------------------------


def test_top_render_frame_live_and_dead_sources():
    snap = {
        "run_id": "r", "best_loss": 0.125, "trials_completed": 42,
        "sections": {
            "report": {"suggest": {"sec": 1.0, "count": 42, "frac": 1.0}},
            "health": {"asks": 5, "last_ei_p50": 0.4,
                       "last_dup_rate": 0.1},
            "utilization": {},
            "ask_pipeline": {"calls": 42, "speculative": 0,
                             "inflight": 2.0,
                             "blocked_sec": {"count": 42, "p50": 0.003}},
        },
        "last_heartbeats": {"fmin.tick": {"age_sec": 0.5, "ts": 1.0}},
        "inflight_trials": [{"tid": 41, "state": "claimed",
                             "age_sec": 0.2}],
        "devmem": {"devices": [{"bytes_in_use": 1 << 30,
                                "bytes_limit": 2 << 30}]},
    }
    histories = {}
    frame1 = top_mod.render_frame(
        [("p0", snap), ("p1", {"error": "URLError: refused"})], histories)
    assert "best 0.125" in frame1
    assert "done 42" in frame1
    assert "inflight 2" in frame1
    assert "hbm 50%" in frame1
    assert "DEAD" in frame1 and "refused" in frame1
    assert "last beat fmin.tick" in frame1
    # trends appear once two refreshes accumulated
    snap2 = json.loads(json.dumps(snap))
    snap2["sections"]["health"]["last_ei_p50"] = 0.6
    frame2 = top_mod.render_frame([("p0", snap2)], histories)
    assert "EI p50" in frame2


def test_top_mid_run_stream_without_final_snapshot():
    """A stream being tailed MID-RUN has no kind="metrics" record yet
    (RunObs.finish() writes it): the dashboard derives the trial count
    from lifecycle events and health gauges from live health records."""
    records = [
        {"kind": "span", "name": "suggest", "ts": 1.0, "wall_sec": 0.1},
        {"kind": "trial_event", "event": "trial_new", "tid": 0, "ts": 1.0},
        {"kind": "trial_event", "event": "trial_finished", "tid": 0,
         "ts": 1.2},
        {"kind": "trial_event", "event": "trial_finished", "tid": 1,
         "ts": 1.4},
        {"kind": "health", "algo": "tpe", "ts": 1.3, "ei_p50": 0.7,
         "dup_rate": 0.05},
    ]
    snap = top_mod.snapshot_from_records(records)
    assert snap["trials_completed"] == 2
    assert snap["sections"]["health"]["asks"] == 1
    assert snap["sections"]["health"]["last_ei_p50"] == 0.7
    assert snap["sections"]["health"]["last_dup_rate"] == 0.05


def test_top_once_over_recorded_stream(tmp_path, capsys):
    path = str(tmp_path / "run.jsonl")
    t = Trials()
    fmin(quad, SPACE, algo=tpe.suggest, max_evals=6, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False, obs=path)
    assert top_mod.main(["--once", path]) == 0
    out = capsys.readouterr().out
    assert "run.jsonl" in out
    assert "asks" in out
    # directory mode expands to the stream
    assert top_mod.main(["--once", str(tmp_path)]) == 0
    assert "run.jsonl" in capsys.readouterr().out
