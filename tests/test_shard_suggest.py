"""ISSUE 6: mesh-sharded fused suggest + compressed device history.

The equivalence doctrine: sharding is a LAYOUT change, not an algorithm
change — at the same seed the sharded fused tell+ask program must propose
bit-identically to the single-chip one, for every mesh shape and for both
history layouts (replicated and capacity-sharded).  bf16 history is a
STORAGE change with an f32 accumulation contract: proposals may differ
from the f32 run (values quantize) but must be deterministic and
round-trip pickle/resume bitwise against an uninterrupted bf16 run.
"""

import functools
import os
import pickle

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hyperopt_tpu import Trials, fmin, hp, quant
from hyperopt_tpu._env import (parse_hist_dtype, parse_hist_shard_min,
                               parse_pallas, parse_shard)
from hyperopt_tpu.algos import rand, tpe
from hyperopt_tpu.base import Domain, PaddedHistory
from hyperopt_tpu.exceptions import StaleHistoryError
from hyperopt_tpu.fmin import FMinIter
from hyperopt_tpu.parallel import sharding

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)

SPACE = {
    "x": hp.uniform("x", -5, 5),
    "lr": hp.loguniform("lr", -4, 0),
    "k": hp.randint("k", 4),
}


def obj(d):
    return (d["x"] - 1.0) ** 2 + d["lr"]


def _populated(n=10):
    t = Trials()
    fmin(obj, SPACE, algo=rand.suggest, max_evals=n, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False)
    return t


def _proposals(n_ids=8, seed=42):
    t = _populated()
    dom = Domain(obj, SPACE)
    docs = tpe.suggest(t.new_trial_ids(n_ids), dom, t, seed,
                       n_startup_jobs=5, n_EI_candidates=64)
    return [d["misc"]["vals"] for d in docs]


# ---------------------------------------------------------------------------
# env knobs
# ---------------------------------------------------------------------------


def test_env_knob_parsing():
    assert parse_shard({}) is None
    assert parse_shard({"HYPEROPT_TPU_SHARD": "0"}) is None
    assert parse_shard({"HYPEROPT_TPU_SHARD": "auto"}) == -1
    assert parse_shard({"HYPEROPT_TPU_SHARD": "4"}) == 4
    assert parse_shard({"HYPEROPT_TPU_SHARD": "1"}) == 1
    assert parse_shard({"HYPEROPT_TPU_SHARD": "soon"}) is None  # warn+off
    assert parse_hist_dtype({}) == "float32"
    assert parse_hist_dtype({"HYPEROPT_TPU_HIST_DTYPE": "bf16"}) == "bfloat16"
    assert parse_hist_dtype({"HYPEROPT_TPU_HIST_DTYPE": "f64"}) == "float32"
    assert parse_hist_dtype({"HYPEROPT_TPU_HIST_DTYPE": "int8"}) == "int8"
    assert parse_hist_dtype({"HYPEROPT_TPU_HIST_DTYPE": "i8"}) == "int8"
    assert parse_hist_dtype({"HYPEROPT_TPU_HIST_DTYPE": "fp8"}) == "fp8"
    assert parse_hist_dtype(
        {"HYPEROPT_TPU_HIST_DTYPE": "float8_e4m3fn"}) == "fp8"
    assert parse_hist_shard_min({}) == 65536
    assert parse_hist_shard_min({"HYPEROPT_TPU_HIST_SHARD_MIN": "128"}) == 128
    assert parse_pallas({}) is False
    assert parse_pallas({"HYPEROPT_TPU_PALLAS": "1"}) is True


# ---------------------------------------------------------------------------
# partition-rule table
# ---------------------------------------------------------------------------


def test_match_partition_rules_maps_history_leaves():
    from jax.sharding import PartitionSpec as P

    rules = sharding.suggest_partition_rules(shard_history=True)
    tree = {"hist": {"losses": 0, "has_loss": 0,
                     "vals": {"x": 0}, "active": {"x": 0}},
            "ids": 0, "rows": 0, "seed_words": 0, "packed": 0}
    specs = sharding.match_partition_rules(rules, tree)
    assert specs["hist"]["losses"] == P((sharding.CAND_AXIS,))
    assert specs["hist"]["vals"]["x"] == P((sharding.CAND_AXIS,))
    assert specs["ids"] == P((sharding.CAND_AXIS,))
    assert specs["rows"] == P()
    # replicated history below the threshold
    rules_rep = sharding.suggest_partition_rules(shard_history=False)
    specs_rep = sharding.match_partition_rules(rules_rep, tree)
    assert specs_rep["hist"]["losses"] == P()
    assert specs_rep["ids"] == P((sharding.CAND_AXIS,))


def test_match_partition_rules_unmatched_leaf_raises():
    with pytest.raises(ValueError, match="no partition rule"):
        sharding.match_partition_rules(
            sharding.suggest_partition_rules(), {"mystery_leaf": 0})


def test_should_shard_history_threshold(monkeypatch):
    mesh = sharding.suggest_mesh(8)
    assert not sharding.should_shard_history(128, mesh)  # below default
    monkeypatch.setenv("HYPEROPT_TPU_HIST_SHARD_MIN", "128")
    assert sharding.should_shard_history(128, mesh)
    assert not sharding.should_shard_history(127, mesh)  # indivisible


# ---------------------------------------------------------------------------
# the equivalence pin: sharded == single-chip, bitwise, mesh {1, 2, 4, 8}
# ---------------------------------------------------------------------------


def test_sharded_suggest_bit_identical_across_mesh_shapes(monkeypatch):
    monkeypatch.delenv("HYPEROPT_TPU_SHARD", raising=False)
    ref = _proposals()
    for shards in (1, 2, 4, 8):
        monkeypatch.setenv("HYPEROPT_TPU_SHARD", str(shards))
        assert _proposals() == ref, f"mesh shape {shards} diverged"


def test_sharded_suggest_bit_identical_with_sharded_history(monkeypatch):
    monkeypatch.delenv("HYPEROPT_TPU_SHARD", raising=False)
    ref = _proposals()
    # force the history axis to shard (cap=128 >> threshold=128)
    monkeypatch.setenv("HYPEROPT_TPU_HIST_SHARD_MIN", "128")
    for shards in (2, 8):
        monkeypatch.setenv("HYPEROPT_TPU_SHARD", str(shards))
        t = _populated()
        dom = Domain(obj, SPACE)
        docs = tpe.suggest(t.new_trial_ids(8), dom, t, 42,
                           n_startup_jobs=5, n_EI_candidates=64)
        assert [d["misc"]["vals"] for d in docs] == ref
        # the resident layout really is capacity-sharded
        ph = t.history_object(dom.cs.labels)
        shard_shape = ph._dev["losses"].addressable_shards[0].data.shape
        assert shard_shape == (ph.cap // shards,)


def test_sharded_donation_in_place_and_stale_guard(monkeypatch):
    monkeypatch.setenv("HYPEROPT_TPU_SHARD", "8")
    t = _populated()
    dom = Domain(obj, SPACE)
    ph = t.history_object(dom.cs.labels)
    # two asks: the first places the mirror in the mesh layout, the second
    # commits a mesh-resident handle whose buffers steady-state donation
    # then reuses in place
    tpe.suggest(t.new_trial_ids(1), dom, t, 17, n_startup_jobs=5)
    tpe.suggest(t.new_trial_ids(1), dom, t, 18, n_startup_jobs=5)
    old = ph._dev

    def shard_ptrs(a):
        return tuple(s.data.unsafe_buffer_pointer()
                     for s in a.addressable_shards)

    ptrs = shard_ptrs(old["losses"])
    tpe.suggest(t.new_trial_ids(1), dom, t, 19, n_startup_jobs=5)
    assert old["losses"].is_deleted()  # donated (consumed), not copied
    assert shard_ptrs(ph._dev["losses"]) == ptrs  # aliased in place
    assert len(ph._dev["losses"].sharding.device_set) == 8

    # StaleHistoryError still guards the sharded donated path
    dev, rows = ph.device_state(donate=True)
    with pytest.raises(StaleHistoryError, match="donated"):
        ph.device_view()
    ph.commit_device(dev)


def test_sharded_suggest_gauges(monkeypatch):
    monkeypatch.setenv("HYPEROPT_TPU_SHARD", "4")
    t = Trials()
    fmin(obj, SPACE, algo=functools.partial(tpe.suggest, n_startup_jobs=6),
         max_evals=12, trials=t, rstate=np.random.default_rng(0),
         show_progressbar=False)
    snap = t.obs_metrics.snapshot()["metrics"]
    assert snap.get("suggest.shards") == 4
    assert snap.get("suggest.cand_per_shard", 0) > 0
    assert snap.get("suggest.hist_sharded") == 0


def test_indivisible_batch_pads_to_mesh(monkeypatch):
    # 8-wide mesh, 3 queued ids: the batch pads to a mesh multiple instead
    # of aborting, extras are discarded on host
    monkeypatch.setenv("HYPEROPT_TPU_SHARD", "8")
    t = _populated()
    dom = Domain(obj, SPACE)
    docs = tpe.suggest(t.new_trial_ids(3), dom, t, 7, n_startup_jobs=5)
    assert len(docs) == 3


# ---------------------------------------------------------------------------
# bf16 compressed history
# ---------------------------------------------------------------------------


def test_bf16_history_halves_resident_bytes(monkeypatch):
    labels = ("a", "b")

    def resident_bytes(dtype):
        ph = PaddedHistory(labels, hist_dtype=dtype)
        for i in range(20):
            ph.append({l: float(i) for l in labels}, float(i))
        dev = ph.device_view()
        return sum(dev["vals"][l].nbytes for l in labels) + dev["losses"].nbytes

    assert resident_bytes("float32") == 2 * resident_bytes("bfloat16")


def test_bf16_history_deterministic_and_valid(monkeypatch):
    monkeypatch.setenv("HYPEROPT_TPU_HIST_DTYPE", "bf16")
    a, b = _proposals(seed=9), _proposals(seed=9)
    assert a == b
    for v in a:
        assert -5 <= v["x"][0] <= 5
        assert np.exp(-4) - 1e-5 <= v["lr"][0] <= 1 + 1e-5
        assert v["k"][0] in range(4)


def test_bf16_pickle_midrun_resumes_bitwise(monkeypatch):
    # the round-trip pin: pickling Trials mid-run with the compressed
    # mirror live and resuming must reproduce the uninterrupted bf16 run
    # (host numpy stays f32 authoritative; the dtype travels in the pickle)
    monkeypatch.setenv("HYPEROPT_TPU_HIST_DTYPE", "bf16")
    algo = functools.partial(tpe.suggest, n_startup_jobs=6)

    def make_iter(trials, rng):
        return FMinIter(algo, Domain(obj, SPACE), trials, rstate=rng,
                        max_evals=20, show_progressbar=False)

    t_full = Trials()
    make_iter(t_full, np.random.default_rng(3)).run(20)

    rng = np.random.default_rng(3)
    t_a = Trials()
    make_iter(t_a, rng).run(12)
    labels = Domain(obj, SPACE).cs.labels
    ph = t_a.history_object(labels)
    assert ph._dev is not None and ph._dev["losses"].dtype == jnp.bfloat16
    t_b = pickle.loads(pickle.dumps(t_a))
    assert t_b._history is None  # device state never traveled
    make_iter(t_b, rng).run(8)
    assert [d["misc"]["vals"] for d in t_b.trials] == \
        [d["misc"]["vals"] for d in t_full.trials]
    np.testing.assert_array_equal(t_b.losses(), t_full.losses())
    # host arrays (the pickle payload) stayed f32
    assert t_b.history_object(labels)._losses.dtype == np.float32


def test_bf16_checkpoint_resume_multihost_single(tmp_path, monkeypatch):
    # driver checkpoint/resume with the compressed device mirror: the
    # checkpoint pickles the f32 host fold, so a resumed bf16 run replays
    # to the same checksum as an uninterrupted one
    monkeypatch.setenv("HYPEROPT_TPU_HIST_DTYPE", "bf16")
    from hyperopt_tpu.parallel.driver import fmin_multihost
    from hyperopt_tpu.zoo import ZOO

    dom = ZOO["branin"]
    f = lambda d: float(dom.objective(d))  # noqa: E731
    ck = str(tmp_path / "ck.pkl")
    full = fmin_multihost(f, dom.space, max_evals=24, batch=8, seed=0,
                          _force_single=True)
    fmin_multihost(f, dom.space, max_evals=16, batch=8, seed=0,
                   checkpoint_file=ck, _force_single=True)
    resumed = fmin_multihost(f, dom.space, max_evals=24, batch=8, seed=0,
                             checkpoint_file=ck, _force_single=True)
    assert resumed.checksum == full.checksum
    np.testing.assert_array_equal(resumed.losses, full.losses)


def test_device_loop_chunk_sharded_state(monkeypatch):
    # the device-loop chunk program compiles with explicit NamedShardings
    # when armed past the threshold: cap-axis-sharded loop state, the run
    # still completes and optimizes
    monkeypatch.setenv("HYPEROPT_TPU_SHARD", "8")
    monkeypatch.setenv("HYPEROPT_TPU_HIST_SHARD_MIN", "128")
    from hyperopt_tpu.zoo import ZOO

    dom = ZOO["branin"]
    t = Trials()
    fmin(dom.objective, dom.space, max_evals=40, trials=t, device_loop=True,
         rstate=np.random.default_rng(0), show_progressbar=False)
    assert len(t) == 40
    assert min(l for l in t.losses() if l is not None) < 5.0


# ---------------------------------------------------------------------------
# int8/fp8 quantized history (ISSUE 19)
# ---------------------------------------------------------------------------

_QUANT_NAMES = ("int8", "fp8")


def _skip_unless_backend(qname):
    if quant.vals_dtype(qname) is None:
        pytest.skip(f"backend lacks the {qname} storage dtype")


def test_int8_history_quarter_resident_bytes():
    # the acceptance bar: int8 history <= 0.3x f32 bytes at equal cap
    # (vals go 4 -> 1 byte; losses go 4 -> 2, bf16 — data-dependent range
    # rules out a static loss scale).  Needs >= 6 labels for the loss
    # floor to amortize under 0.3.
    space = {f"x{i}": hp.uniform(f"x{i}", -5, 5) for i in range(6)}
    cs = Domain(None, space).cs

    def resident_bytes(dtype):
        ph = PaddedHistory(cs.labels, hist_dtype=dtype)
        ph.ensure_qparams(cs)
        for i in range(20):
            ph.append({l: float(i % 5) - 2.0 for l in cs.labels}, float(i))
        dev = ph.device_view()
        return (sum(dev["vals"][l].nbytes for l in cs.labels)
                + dev["losses"].nbytes)

    f32, i8 = resident_bytes("float32"), resident_bytes("int8")
    assert i8 / f32 <= 0.3, (i8, f32)


@pytest.mark.parametrize("qname", _QUANT_NAMES)
def test_quant_mirror_really_quantized(monkeypatch, qname):
    _skip_unless_backend(qname)
    monkeypatch.setenv("HYPEROPT_TPU_HIST_DTYPE", qname)
    t = _populated()
    dom = Domain(obj, SPACE)
    tpe.suggest(t.new_trial_ids(4), dom, t, 5, n_startup_jobs=5)
    ph = t.history_object(dom.cs.labels)
    assert ph.hist_dtype == qname and ph.qparams is not None
    assert ph._dev["vals"]["x"].dtype == quant.vals_dtype(qname)
    assert ph._dev["losses"].dtype == jnp.bfloat16
    # host numpy (the pickle payload) stays f32 authoritative
    assert ph._losses.dtype == np.float32


@pytest.mark.parametrize("qname", _QUANT_NAMES)
def test_quant_history_deterministic_and_valid(monkeypatch, qname):
    _skip_unless_backend(qname)
    monkeypatch.setenv("HYPEROPT_TPU_HIST_DTYPE", qname)
    a, b = _proposals(seed=9), _proposals(seed=9)
    assert a == b
    for v in a:
        assert -5 <= v["x"][0] <= 5
        assert np.exp(-4) - 1e-5 <= v["lr"][0] <= 1 + 1e-5
        assert v["k"][0] in range(4)


@pytest.mark.parametrize("qname", _QUANT_NAMES)
def test_quant_pickle_midrun_resumes_bitwise(monkeypatch, qname):
    # ISSUE 19 round-trip pin: pickling Trials mid-run with the QUANTIZED
    # mirror live and resuming reproduces the uninterrupted same-dtype
    # run bitwise — values snap to the code grid at ingest, so the doc
    # stream (the pickle payload) already lives on the grid and a rebuilt
    # mirror re-encodes to the same codes.
    _skip_unless_backend(qname)
    monkeypatch.setenv("HYPEROPT_TPU_HIST_DTYPE", qname)
    algo = functools.partial(tpe.suggest, n_startup_jobs=6)

    def make_iter(trials, rng):
        return FMinIter(algo, Domain(obj, SPACE), trials, rstate=rng,
                        max_evals=20, show_progressbar=False)

    t_full = Trials()
    make_iter(t_full, np.random.default_rng(3)).run(20)

    rng = np.random.default_rng(3)
    t_a = Trials()
    make_iter(t_a, rng).run(12)
    labels = Domain(obj, SPACE).cs.labels
    ph = t_a.history_object(labels)
    assert ph._dev is not None
    assert ph._dev["vals"]["x"].dtype == quant.vals_dtype(qname)
    t_b = pickle.loads(pickle.dumps(t_a))
    assert t_b._history is None  # device codes never travel
    make_iter(t_b, rng).run(8)
    assert [d["misc"]["vals"] for d in t_b.trials] == \
        [d["misc"]["vals"] for d in t_full.trials]
    np.testing.assert_array_equal(t_b.losses(), t_full.losses())


def test_quant_unsupported_space_degrades_to_bf16(monkeypatch):
    # a q* family's value grid is not affine in t-space: the quantizer
    # refuses, the WHOLE mirror degrades to bf16 (warn-once + counter),
    # and the ask is served normally — degrade never fails an ask
    monkeypatch.setenv("HYPEROPT_TPU_HIST_DTYPE", "int8")
    space = {"x": hp.uniform("x", -5, 5), "q": hp.quniform("q", 0, 10, 2)}

    def qobj(d):
        return d["x"] ** 2 + 0.1 * d["q"]

    before = quant.fallback_count()
    t = Trials()
    fmin(qobj, space, algo=rand.suggest, max_evals=8, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False)
    dom = Domain(qobj, space)
    docs = tpe.suggest(t.new_trial_ids(4), dom, t, 5, n_startup_jobs=5)
    assert len(docs) == 4
    ph = t.history_object(dom.cs.labels)
    assert ph.hist_dtype == "bfloat16" and ph.qparams is None
    assert ph._dev["losses"].dtype == jnp.bfloat16
    assert quant.fallback_count() > before


# ---------------------------------------------------------------------------
# pallas EI opt-in
# ---------------------------------------------------------------------------


def test_pallas_optin_matches_default_path(monkeypatch):
    # CPU: ei_diff falls back to the jnp twin — same math as the default
    # lpdf difference up to fp reassociation; proposals must agree closely
    # and be deterministic.  The DEFAULT (flag off) path is byte-untouched:
    # same kernels as before this round (covered by every other test).
    monkeypatch.delenv("HYPEROPT_TPU_PALLAS", raising=False)
    t = _populated()
    hist = t.history_object(Domain(obj, SPACE).cs.labels).device_view()
    hist = {k: hist[k] for k in ("losses", "has_loss", "vals", "active")}
    cs = Domain(obj, SPACE).cs
    cfg = {"prior_weight": 1.0, "n_EI_candidates": 64, "gamma": 0.25,
           "LF": 25}
    key = jax.random.PRNGKey(11)
    raw_off = tpe.build_propose_candidates(cs, cfg)(hist, key)
    monkeypatch.setenv("HYPEROPT_TPU_PALLAS", "1")
    raw_on = tpe.build_propose_candidates(cs, cfg)(hist, key)
    for label in cs.labels:
        s_off, ei_off = raw_off[label]
        s_on, ei_on = raw_on[label]
        np.testing.assert_array_equal(np.asarray(s_off), np.asarray(s_on))
        fin = np.isfinite(np.asarray(ei_off))
        np.testing.assert_allclose(np.asarray(ei_on)[fin],
                                   np.asarray(ei_off)[fin],
                                   rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# per-shard devmem breakdown
# ---------------------------------------------------------------------------


def test_devmem_per_device_breakdown(monkeypatch):
    from hyperopt_tpu.obs import ObsConfig, RunObs
    from hyperopt_tpu.obs.devmem import DevMemSampler

    monkeypatch.setenv("HYPEROPT_TPU_SHARD", "8")
    monkeypatch.setenv("HYPEROPT_TPU_HIST_SHARD_MIN", "128")
    t = _populated()
    dom = Domain(obj, SPACE)
    tpe.suggest(t.new_trial_ids(8), dom, t, 3, n_startup_jobs=5)
    obs = RunObs(ObsConfig(level="basic"), run_id="shard-devmem")
    sampler = DevMemSampler(obs, period=0.0)
    rec = sampler.sample(reason="test")
    obs.finish()
    assert rec is not None and "per_device" in rec
    # history bytes are attributed across all 8 devices
    devs_with_history = [d for d, owners in rec["per_device"].items()
                         if "history" in owners]
    assert len(devs_with_history) == 8