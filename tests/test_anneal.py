"""Anneal + mix suggester tests (parity targets: hyperopt/tests/test_anneal.py,
hyperopt/mix.py)."""

import functools

import numpy as np
import pytest

from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu.algos import anneal, mix, rand, tpe
from hyperopt_tpu.zoo import ZOO


def _best_loss(domain, algo, seed, max_evals):
    t = Trials()
    fmin(domain.objective, domain.space, algo=algo, max_evals=max_evals,
         trials=t, rstate=np.random.default_rng(seed), show_progressbar=False)
    return min(l for l in t.losses() if l is not None)


def test_anneal_beats_random_on_quadratic():
    domain = ZOO["quadratic1"]
    seeds = range(4)
    a = np.mean([_best_loss(domain, anneal.suggest, s, 60) for s in seeds])
    r = np.mean([_best_loss(domain, rand.suggest, s, 60) for s in seeds])
    assert a <= r * 1.05 + 1e-3, (a, r)


def test_anneal_converges_tightly():
    domain = ZOO["quadratic1"]
    best = min(_best_loss(domain, anneal.suggest, s, 100) for s in range(3))
    assert best < domain.loss_target


def test_anneal_conditional_space():
    space = hp.choice("c", [
        {"kind": "a", "x": hp.uniform("xa", -5, 5)},
        {"kind": "b", "y": hp.uniform("yb", 5, 10)},
    ])

    def obj(d):
        return (d["x"] - 2.0) ** 2 if d["kind"] == "a" else d["y"]

    t = Trials()
    fmin(obj, space, algo=anneal.suggest, max_evals=60, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False)
    best = t.best_trial
    assert best["result"]["loss"] < 1.5
    assert best["misc"]["vals"]["c"] == [0]


def test_anneal_mixed_families_smoke():
    domain = ZOO["many_dists"]
    loss = _best_loss(domain, anneal.suggest, 0, 30)
    assert np.isfinite(loss)


def test_anneal_tunable_like_reference():
    algo = anneal.AnnealSuggest(avg_best_idx=3.0, shrink_coef=0.2)
    loss = _best_loss(ZOO["quadratic1"], algo, 0, 50)
    assert loss < 1.0


def test_anneal_respects_bounds():
    t = Trials()
    space = {"x": hp.uniform("x", -1, 1), "q": hp.quniform("q", 0, 10, 2)}
    fmin(lambda d: d["x"] ** 2 + d["q"] * 0.01, space, algo=anneal.suggest,
         max_evals=60, trials=t, rstate=np.random.default_rng(0),
         show_progressbar=False)
    xs = np.array([m["vals"]["x"][0] for m in t.miscs])
    qs = np.array([m["vals"]["q"][0] for m in t.miscs])
    assert xs.min() >= -1 and xs.max() <= 1
    np.testing.assert_allclose(qs, np.round(qs / 2) * 2, atol=1e-5)


def test_mix_dispatches_by_probability():
    calls = {"a": 0, "b": 0}

    def make(tag):
        def algo(new_ids, domain, trials, seed):
            calls[tag] += len(new_ids)
            return rand.suggest(new_ids, domain, trials, seed)

        return algo

    t = Trials()
    fmin(lambda d: d["x"] ** 2, {"x": hp.uniform("x", -1, 1)},
         algo=functools.partial(mix.suggest,
                                p_suggest=[(0.8, make("a")), (0.2, make("b"))]),
         max_evals=100, trials=t, rstate=np.random.default_rng(0),
         show_progressbar=False)
    assert calls["a"] + calls["b"] == 100
    assert calls["a"] > calls["b"]


def test_mix_validates_probabilities():
    with pytest.raises(ValueError):
        mix.suggest([0], None, Trials(), 0, p_suggest=[(0.5, rand.suggest)])


def test_mix_tpe_and_anneal_end_to_end():
    algo = functools.partial(
        mix.suggest, p_suggest=[(0.5, tpe.suggest), (0.5, anneal.suggest)]
    )
    loss = _best_loss(ZOO["branin"], algo, 0, 50)
    assert np.isfinite(loss)
