"""Statistical tests for the jitted TPE kernels.

Mirrors the reference's test doctrine (``hyperopt/tests/test_tpe.py``,
SURVEY.md §4): seed-pinned but *statistical* assertions — lpdf normalization
over the truncated support, sampler↔lpdf agreement, and
optimizer-beats-random — never bitwise golden values (threefry ≠ MT19937,
inversion ≠ rejection).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu.algos import rand, tpe
from hyperopt_tpu.zoo import ZOO


def _mask(n, cap=64):
    m = np.zeros(cap, bool)
    m[:n] = True
    return jnp.asarray(m)


def _obs(values, cap=64):
    v = np.zeros(cap, np.float32)
    v[: len(values)] = values
    return jnp.asarray(v), _mask(len(values), cap)


# ---------------------------------------------------------------------------
# linear forgetting
# ---------------------------------------------------------------------------


def test_linear_forgetting_all_ones_when_small():
    w = tpe.linear_forgetting_weights(_mask(10), LF=25)
    np.testing.assert_allclose(np.asarray(w)[:10], 1.0)
    np.testing.assert_allclose(np.asarray(w)[10:], 0.0)


def test_linear_forgetting_ramp():
    n, LF = 40, 25
    w = np.asarray(tpe.linear_forgetting_weights(_mask(n), LF=LF))[:n]
    # newest LF at weight 1; oldest n-LF ramp from 1/n up
    np.testing.assert_allclose(w[n - LF :], 1.0)
    ref_ramp = np.linspace(1.0 / n, 1.0, n - LF)
    np.testing.assert_allclose(w[: n - LF], ref_ramp, rtol=1e-5)


# ---------------------------------------------------------------------------
# adaptive parzen fit
# ---------------------------------------------------------------------------


def test_adaptive_parzen_empty_is_prior():
    obs, mask = _obs([])
    w, mu, sig = tpe.adaptive_parzen_normal(obs, mask, 1.0, 0.5, 2.0, 25)
    w, mu, sig = map(np.asarray, (w, mu, sig))
    assert w.sum() == pytest.approx(1.0)
    live = w > 0
    assert live.sum() == 1
    assert mu[live][0] == pytest.approx(0.5)
    assert sig[live][0] == pytest.approx(2.0)


def test_adaptive_parzen_shapes_and_clipping():
    values = [1.0, 1.1, 4.0, -2.0, 0.3]
    obs, mask = _obs(values)
    prior_mu, prior_sigma = 0.0, 10.0
    w, mu, sig = tpe.adaptive_parzen_normal(obs, mask, 1.0, prior_mu, prior_sigma, 25)
    w, mu, sig = map(np.asarray, (w, mu, sig))
    assert w.sum() == pytest.approx(1.0, abs=1e-5)
    m = len(values) + 1
    assert (w > 0).sum() == m
    live_mu = mu[w > 0]
    assert np.all(np.diff(live_mu) >= 0)  # sorted
    np.testing.assert_allclose(live_mu, np.sort(values + [prior_mu]), atol=1e-5)
    minsigma = prior_sigma / min(100.0, 1.0 + m)
    assert np.all(sig[w > 0] >= minsigma - 1e-6)
    assert np.all(sig[w > 0] <= prior_sigma + 1e-6)


def test_adaptive_parzen_duplicate_obs_get_min_sigma():
    # duplicates have zero neighbor gaps; their sigma must clip to MINsigma,
    # not fall back to prior_sigma (else TPE can't concentrate on repeated
    # good values of quantized params)
    obs, mask = _obs([5.0, 5.0, 5.0, 5.0])
    w, mu, sig = tpe.adaptive_parzen_normal(obs, mask, 1.0, 5.0, 10.0, 25)
    w, mu, sig = map(np.asarray, (w, mu, sig))
    minsigma = 10.0 / min(100.0, 1.0 + 5)
    dup = (w > 0) & (np.abs(mu - 5.0) < 1e-6)
    assert (sig[dup] <= minsigma + 1e-5).sum() >= 4


def test_gmm1_sample_boundary_candidates_score_finite():
    # tight component at the upper bound: inverse-CDF samples clamp just
    # inside [low, high) so their lpdf stays finite (no NaN EI)
    obs, mask = _obs([4.999, 4.9995, 4.9999])
    w, mu, sig = tpe.adaptive_parzen_normal(obs, mask, 1.0, 2.5, 5.0, 25)
    xs = tpe.gmm1_sample(jax.random.PRNGKey(0), w, mu, sig, 0.0, 5.0, None, 10_000)
    lp = tpe.gmm1_lpdf(xs, w, mu, sig, 0.0, 5.0, None)
    assert bool(jnp.all(jnp.isfinite(lp)))
    assert float(jnp.max(xs)) < 5.0


def test_adaptive_parzen_prior_keeps_prior_sigma():
    obs, mask = _obs([0.001, 0.002, 0.003])
    w, mu, sig = tpe.adaptive_parzen_normal(obs, mask, 1.0, 0.0, 5.0, 25)
    mu, sig, w = map(np.asarray, (mu, sig, w))
    # the component at the prior location keeps sigma = prior_sigma
    prior_idx = np.argmin(np.abs(mu - 0.0) + (w <= 0) * 1e9)
    assert sig[prior_idx] == pytest.approx(5.0)


# ---------------------------------------------------------------------------
# GMM sample + lpdf
# ---------------------------------------------------------------------------


def _fit(values, prior_mu, prior_sigma, cap=64):
    obs, mask = _obs(values, cap)
    return tpe.adaptive_parzen_normal(obs, mask, 1.0, prior_mu, prior_sigma, 25)


def test_gmm1_lpdf_integrates_to_one():
    w, mu, sig = _fit([1.0, 2.0, 4.5, -1.0], 0.0, 6.0)
    low, high = -5.0, 5.0
    xs = jnp.linspace(low, high, 20001)
    lp = tpe.gmm1_lpdf(xs, w, mu, sig, low, high, None)
    integral = jnp.trapezoid(jnp.exp(lp), xs)
    assert float(integral) == pytest.approx(1.0, abs=2e-3)


def test_gmm1_lpdf_quantized_sums_to_one():
    q = 0.5
    low, high = 0.0, 10.0
    w, mu, sig = _fit([2.0, 2.5, 7.0], 5.0, 10.0)
    bins = jnp.arange(0.0, 10.0 + q / 2, q)
    lp = tpe.gmm1_lpdf(bins, w, mu, sig, low, high, q)
    total = jnp.sum(jnp.exp(lp))
    assert float(total) == pytest.approx(1.0, abs=2e-3)


def test_gmm1_sample_within_bounds_and_matches_lpdf():
    w, mu, sig = _fit([1.0, 2.0, 4.5], 2.5, 5.0)
    low, high = 0.0, 5.0
    key = jax.random.PRNGKey(0)
    xs = np.asarray(tpe.gmm1_sample(key, w, mu, sig, low, high, None, 200_000))
    assert xs.min() >= low and xs.max() <= high
    # compare empirical bin masses against lpdf-integrated masses
    edges = np.linspace(low, high, 21)
    emp, _ = np.histogram(xs, bins=edges, density=False)
    emp = emp / emp.sum()
    centers = (edges[:-1] + edges[1:]) / 2
    lp = np.asarray(tpe.gmm1_lpdf(jnp.asarray(centers), w, mu, sig, low, high, None))
    model = np.exp(lp)
    model = model / model.sum()
    assert np.max(np.abs(emp - model)) < 0.01


def test_gmm1_sample_quantized_on_grid():
    w, mu, sig = _fit([2.0, 3.0], 2.5, 5.0)
    xs = np.asarray(
        tpe.gmm1_sample(jax.random.PRNGKey(1), w, mu, sig, 0.0, 5.0, 0.5, 10_000)
    )
    np.testing.assert_allclose(xs, np.round(xs / 0.5) * 0.5, atol=1e-5)


def test_lgmm1_lpdf_integrates_to_one():
    # log-space bounds [-1, 2] -> value support [e^-1, e^2]
    w, mu, sig = _fit(np.log([1.0, 2.0, 5.0]), 0.5, 3.0)
    low, high = -1.0, 2.0
    xs = jnp.linspace(np.exp(low) + 1e-4, np.exp(high) - 1e-4, 40001)
    lp = tpe.lgmm1_lpdf(xs, w, mu, sig, low, high, None)
    integral = jnp.trapezoid(jnp.exp(lp), xs)
    assert float(integral) == pytest.approx(1.0, abs=5e-3)


def test_lgmm1_sample_bounds_and_histogram():
    w, mu, sig = _fit(np.log([1.0, 3.0]), 0.5, 3.0)
    low, high = -1.0, 2.0
    xs = np.asarray(
        tpe.lgmm1_sample(jax.random.PRNGKey(2), w, mu, sig, low, high, None, 200_000)
    )
    assert xs.min() >= np.exp(low) - 1e-4
    assert xs.max() <= np.exp(high) + 1e-4
    edges = np.linspace(np.exp(low), np.exp(high), 21)
    emp, _ = np.histogram(xs, bins=edges)
    emp = emp / emp.sum()
    centers = (edges[:-1] + edges[1:]) / 2
    model = np.exp(np.asarray(tpe.lgmm1_lpdf(jnp.asarray(centers), w, mu, sig, low, high, None)))
    model = model / model.sum()
    assert np.max(np.abs(emp - model)) < 0.015


def test_lgmm1_lpdf_quantized_includes_zero_bin():
    w, mu, sig = _fit(np.log([1.0, 2.0]), 0.0, 2.0)
    q = 1.0
    bins = jnp.arange(0.0, 2000.0, q)  # heavy lognormal tail: go far out
    lp = tpe.lgmm1_lpdf(bins, w, mu, sig, -jnp.inf, jnp.inf, q)
    total = float(jnp.sum(jnp.exp(lp)))
    assert total == pytest.approx(1.0, abs=5e-3)
    # the zero bin [0, q/2) carries real mass and a finite lpdf
    assert np.isfinite(float(lp[0]))


# ---------------------------------------------------------------------------
# categorical posterior
# ---------------------------------------------------------------------------


def test_categorical_posterior_prior_only():
    obs, mask = _obs([])
    p = jnp.asarray([0.2, 0.3, 0.5])
    post = np.asarray(tpe.categorical_posterior(obs, mask, p, 1.0, 25))
    np.testing.assert_allclose(post, [0.2, 0.3, 0.5], atol=1e-6)


def test_categorical_posterior_counts_dominate():
    obs, mask = _obs([1.0] * 50)
    p = jnp.asarray([1 / 3, 1 / 3, 1 / 3])
    post = np.asarray(tpe.categorical_posterior(obs, mask, p, 1.0, 100))
    assert post[1] > 0.9
    assert post.sum() == pytest.approx(1.0, abs=1e-6)


# ---------------------------------------------------------------------------
# below/above split
# ---------------------------------------------------------------------------


def test_split_below_above_counts():
    cap = 64
    losses = np.full(cap, np.inf, np.float32)
    has = np.zeros(cap, bool)
    N = 36
    rng = np.random.default_rng(0)
    losses[:N] = rng.normal(size=N)
    has[:N] = True
    below, above = tpe.split_below_above(
        jnp.asarray(losses), jnp.asarray(has), 0.25, 25
    )
    below, above = np.asarray(below), np.asarray(above)
    n_below = min(int(np.ceil(0.25 * np.sqrt(N))), 25)
    assert below.sum() == n_below
    assert above.sum() == N - n_below
    # below trials are exactly the n_below smallest losses
    assert losses[below].max() <= losses[above].min()


# ---------------------------------------------------------------------------
# end-to-end: TPE beats random within a fixed budget
# ---------------------------------------------------------------------------


def _best_loss(domain, algo, seed, max_evals):
    t = Trials()
    fmin(
        domain.objective,
        domain.space,
        algo=algo,
        max_evals=max_evals,
        trials=t,
        rstate=np.random.default_rng(seed),
        show_progressbar=False,
    )
    return min(l for l in t.losses() if l is not None)


@pytest.mark.parametrize("name,budget", [("quadratic1", 60), ("branin", 75)])
def test_tpe_beats_random(name, budget):
    domain = ZOO[name]
    seeds = range(4)
    tpe_best = np.mean([_best_loss(domain, tpe.suggest, s, budget) for s in seeds])
    rand_best = np.mean([_best_loss(domain, rand.suggest, s, budget) for s in seeds])
    assert tpe_best <= rand_best * 1.05 + 1e-3, (tpe_best, rand_best)


def test_tpe_reaches_branin_target():
    domain = ZOO["branin"]
    best = min(_best_loss(domain, tpe.suggest, s, 100) for s in range(3))
    assert best < domain.loss_target


def test_tpe_conditional_space_picks_good_branch():
    space = hp.choice(
        "c",
        [
            {"kind": "a", "x": hp.uniform("xa", -5, 5)},
            {"kind": "b", "y": hp.uniform("yb", 5, 10)},
        ],
    )

    def obj(d):
        return (d["x"] - 2.0) ** 2 if d["kind"] == "a" else d["y"]

    t = Trials()
    fmin(obj, space, algo=tpe.suggest, max_evals=60, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False)
    best = t.best_trial
    assert best["result"]["loss"] < 1.0
    assert best["misc"]["vals"]["c"] == [0]


def test_tpe_partial_tuning_works():
    import functools

    domain = ZOO["quadratic1"]
    algo = functools.partial(tpe.suggest, gamma=0.5, n_EI_candidates=64, n_startup_jobs=10)
    loss = _best_loss(domain, algo, 0, 40)
    assert loss < 1.0


def test_tpe_many_dists_smoke():
    domain = ZOO["many_dists"]
    loss = _best_loss(domain, tpe.suggest, 0, 40)
    assert np.isfinite(loss)


def test_grouped_uniform_pipeline_matches_per_label():
    # build_propose(group=True) routes hp.uniform labels through ONE vmapped
    # pipeline; proposals must match the unrolled per-label path (same math,
    # same per-label fold_in keys) on a mixed conditional space
    import jax

    from hyperopt_tpu.spaces import compile_space

    space = {
        **{f"u{i}": hp.uniform(f"u{i}", -5 + i, 5 + i) for i in range(5)},
        "lg": hp.loguniform("lg", -4, 0),
        "q": hp.quniform("q", 0, 10, 2),
        "c": hp.choice("c", [{"w": hp.uniform("w", 0, 1)},
                             {"z": hp.randint("z", 5)}]),
    }
    cs = compile_space(space)
    cfg = {"prior_weight": 1.0, "n_EI_candidates": 64, "gamma": 0.25, "LF": 25}
    rng = np.random.default_rng(0)
    cap, n_obs = 64, 40
    has = np.zeros(cap, bool)
    has[:n_obs] = True
    keys = jax.vmap(lambda i: jax.random.fold_in(jax.random.PRNGKey(0), i))(
        jnp.arange(cap, dtype=jnp.uint32))
    flats = jax.jit(jax.vmap(cs.sample_flat))(keys)
    acts = jax.vmap(cs.active_flat)(flats)
    hist = {
        "losses": jnp.asarray(
            np.where(has, rng.normal(size=cap), np.inf).astype(np.float32)),
        "has_loss": jnp.asarray(has),
        "vals": {l: jnp.asarray(np.asarray(flats[l], np.float32))
                 for l in cs.labels},
        "active": {l: jnp.asarray(np.asarray(acts[l]) & has)
                   for l in cs.labels},
    }
    pk = jax.random.PRNGKey(7)
    out_g = jax.jit(tpe.build_propose(cs, cfg, group=True))(hist, pk)
    out_p = jax.jit(tpe.build_propose(cs, cfg, group=False))(hist, pk)
    for label in cs.labels:
        np.testing.assert_allclose(
            np.asarray(out_g[label]), np.asarray(out_p[label]),
            rtol=1e-5, atol=1e-5, err_msg=label)


# ---------------------------------------------------------------------------
# NumPy <-> JAX formula parity (round-5 verdict #5): bench.py carries a
# faithful numpy reimplementation of the reference hot path
# (hyperopt/tpe.py sym: adaptive_parzen_normal, GMM1_lpdf); the jitted
# kernels must reproduce its *formulas* on shared inputs — this catches
# algebra drift that distribution-level statistical tests cannot.
# ---------------------------------------------------------------------------


def _np_ref():
    import sys
    import os

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench

    return bench


@pytest.mark.parametrize("n_obs", [1, 3, 24, 60])
def test_adaptive_parzen_matches_numpy_reference(n_obs):
    bench = _np_ref()
    rng = np.random.default_rng(42 + n_obs)
    mus = rng.uniform(-5, 5, size=n_obs)
    prior_mu, prior_sigma, LF = 0.0, 10.0, 25
    w_np, m_np, s_np = bench.np_adaptive_parzen_normal(
        mus, 1.0, prior_mu, prior_sigma, LF=LF)

    obs, mask = _obs(mus.astype(np.float32))
    w_j, m_j, s_j = tpe.adaptive_parzen_normal(
        obs, mask, 1.0, jnp.float32(prior_mu), jnp.float32(prior_sigma), LF)
    m = n_obs + 1  # live components incl. prior
    w_j, m_j, s_j = (np.asarray(a)[:m] for a in (w_j, m_j, s_j))
    # the reference's 1-obs special case (obs sigma = prior_sigma/2) is
    # deliberately subsumed by the general clip (documented substitution in
    # adaptive_parzen_normal's docstring) — exclude sigmas for n_obs==1
    np.testing.assert_allclose(w_j, w_np, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(m_j, m_np, rtol=2e-5, atol=2e-5)
    if n_obs > 1:
        np.testing.assert_allclose(s_j, s_np, rtol=2e-4, atol=2e-4)
    # dead slots carry no weight
    assert np.asarray(tpe.adaptive_parzen_normal(
        obs, mask, 1.0, jnp.float32(prior_mu), jnp.float32(prior_sigma), LF
    )[0])[m:].sum() == 0.0


def test_gmm1_lpdf_matches_numpy_reference():
    bench = _np_ref()
    rng = np.random.default_rng(7)
    n_comp = 9
    weights = rng.uniform(0.1, 1.0, n_comp)
    weights /= weights.sum()
    mus = np.sort(rng.uniform(-4, 4, n_comp))
    sigmas = rng.uniform(0.3, 2.0, n_comp)
    low, high = -5.0, 5.0
    x = rng.uniform(low, high - 1e-3, 257)

    ref = bench.np_gmm1_lpdf(x, weights, mus, sigmas, low, high)
    got = np.asarray(tpe.gmm1_lpdf(
        jnp.asarray(x, jnp.float32), jnp.asarray(weights, jnp.float32),
        jnp.asarray(mus, jnp.float32), jnp.asarray(sigmas, jnp.float32),
        low, high, None))
    np.testing.assert_allclose(got, ref, rtol=5e-4, atol=5e-4)


# ---------------------------------------------------------------------------
# batch diversity (round-5 verdict #1): stochastic EI selection + eps-prior
# mixing keep a wide batch of proposals (one shared posterior) diverse
# ---------------------------------------------------------------------------


def _diversity_hist(cap=64, n_obs=40, seed=0):
    rng = np.random.default_rng(seed)
    has = np.zeros(cap, bool)
    has[:n_obs] = True
    vals = np.where(has, rng.uniform(-5, 5, cap), 0).astype(np.float32)
    # losses correlate with |x - 2|: the below model concentrates near 2
    losses = np.where(has, np.abs(vals - 2.0) + 0.1 * rng.normal(size=cap),
                      np.inf).astype(np.float32)
    return {
        "losses": jnp.asarray(losses),
        "has_loss": jnp.asarray(has),
        "vals": {"x": jnp.asarray(vals)},
        "active": {"x": jnp.asarray(has)},
    }


def _batch_propose(cfg, batch=512):
    from hyperopt_tpu.spaces import compile_space

    cs = compile_space({"x": hp.uniform("x", -5, 5)})
    hist = _diversity_hist()
    propose = jax.jit(jax.vmap(tpe.build_propose(cs, cfg), in_axes=(None, 0)))
    keys = jax.vmap(lambda i: jax.random.fold_in(jax.random.PRNGKey(3), i))(
        jnp.arange(batch, dtype=jnp.uint32))
    return np.asarray(propose(hist, keys)["x"])


def test_softmax_selection_diversifies_shared_posterior_batch():
    base = {"prior_weight": 1.0, "n_EI_candidates": 64, "gamma": 0.25, "LF": 64}
    hard = _batch_propose(base)
    soft = _batch_propose(dict(base, ei_select="softmax", ei_tau=1.0))
    # argmax collapses a shared-posterior batch; softmax must spread it
    assert np.std(soft) > np.std(hard)
    assert len(np.unique(np.round(soft, 3))) > len(np.unique(np.round(hard, 3)))
    # ...while still exploiting: the batch mean stays near the good region
    assert abs(np.mean(soft) - 2.0) < 1.5
    # and stays deterministic in the keys
    soft2 = _batch_propose(dict(base, ei_select="softmax", ei_tau=1.0))
    np.testing.assert_array_equal(soft, soft2)


def test_prior_eps_mixes_in_prior_draws():
    base = {"prior_weight": 1.0, "n_EI_candidates": 64, "gamma": 0.25,
            "LF": 64, "ei_select": "softmax", "ei_tau": 0.5}
    pure = _batch_propose(base)
    mixed = _batch_propose(dict(base, prior_eps=1.0))
    # eps=1: every proposal is a prior draw -> close to uniform over [-5, 5)
    assert np.min(mixed) < -4.0 and np.max(mixed) > 4.0
    ks = np.max(np.abs(np.sort((mixed + 5) / 10) - np.linspace(0, 1, len(mixed))))
    assert ks < 0.08, ks
    # eps=0 keeps the posterior-shaped batch
    assert np.std(pure) < np.std(mixed)


def test_categorical_posterior_floor():
    # the EPS clamp in _propose_discrete must never bind: prior smoothing
    # (+ K * prior_weight * prior_p) lower-bounds every bucket's posterior
    obs, mask = _obs([1.0] * 60)  # all mass on bucket 1
    p = jnp.asarray([0.01, 0.98, 0.01])
    post = np.asarray(tpe.categorical_posterior(obs, mask, p, 1.0, 100))
    K = 3
    total = 60.0 + K * 1.0  # counts + smoothing mass
    floor = K * 1.0 * 0.01 / total
    assert post.min() >= floor - 1e-7
    assert post.min() > 1e6 * tpe.EPS  # clamp is a NaN guard, never binds


@pytest.mark.parametrize("select_cfg", [
    {},
    {"ei_select": "softmax", "ei_tau": 0.7, "prior_eps": 0.3},
])
def test_grouped_pipelines_match_per_label_all_families(select_cfg):
    # round-5: grouping extends beyond hp.uniform to every numeric family
    # (quantized/log/bounds as traced statics) and discrete labels sharing a
    # bucket count.  Each group's vmapped pipeline must reproduce the
    # unrolled per-label kernels, including stochastic selection and
    # eps-prior mixing (same per-label fold_in keys both ways).
    from hyperopt_tpu.spaces import compile_space

    space = {
        # bounded continuous group: uniform + loguniform
        "u1": hp.uniform("u1", -5, 5), "u2": hp.uniform("u2", 0, 1),
        "lg1": hp.loguniform("lg1", -4, 0), "lg2": hp.loguniform("lg2", -2, 2),
        # bounded quantized group: quniform + uniformint + qloguniform
        "q1": hp.quniform("q1", 0, 10, 2), "q2": hp.quniform("q2", -4, 4, 0.5),
        "ui": hp.uniformint("ui", 1, 9), "qlg": hp.qloguniform("qlg", 0, 3, 2),
        # unbounded continuous group: normal + lognormal
        "n1": hp.normal("n1", 0, 2), "n2": hp.normal("n2", 4, 7),
        "ln": hp.lognormal("ln", -1, 1),
        # unbounded quantized group: qnormal + qlognormal
        "qn": hp.qnormal("qn", 0, 10, 2), "qln": hp.qlognormal("qln", 0, 2, 1),
        # discrete groups: two K=3 categoricals, two K=6 randints
        "c1": hp.choice("c1", [0, 1, 2]), "c2": hp.pchoice(
            "c2", [(0.2, 0), (0.3, 1), (0.5, 2)]),
        "r1": hp.randint("r1", 6), "r2": hp.randint("r2", 2, 8),
    }
    cs = compile_space(space)
    cfg = {"prior_weight": 1.0, "n_EI_candidates": 32, "gamma": 0.25,
           "LF": 25, **select_cfg}
    rng = np.random.default_rng(1)
    cap, n_obs = 64, 40
    has = np.zeros(cap, bool)
    has[:n_obs] = True
    keys = jax.vmap(lambda i: jax.random.fold_in(jax.random.PRNGKey(0), i))(
        jnp.arange(cap, dtype=jnp.uint32))
    flats = jax.jit(jax.vmap(cs.sample_flat))(keys)
    hist = {
        "losses": jnp.asarray(
            np.where(has, rng.normal(size=cap), np.inf).astype(np.float32)),
        "has_loss": jnp.asarray(has),
        "vals": {l: jnp.asarray(np.asarray(flats[l], np.float32))
                 for l in cs.labels},
        "active": {l: jnp.asarray(has) for l in cs.labels},
    }
    pk = jax.random.PRNGKey(11)
    out_g = jax.jit(tpe.build_propose(cs, cfg, group=True))(hist, pk)
    out_p = jax.jit(tpe.build_propose(cs, cfg, group=False))(hist, pk)
    for label in cs.labels:
        np.testing.assert_allclose(
            np.asarray(out_g[label]), np.asarray(out_p[label]),
            rtol=1e-5, atol=1e-5, err_msg=label)
