"""Random-suggester tests (parity target: hyperopt/tests/test_rand.py)."""

import numpy as np

from hyperopt_tpu import Domain, Trials, fmin, hp
from hyperopt_tpu.algos import rand


def _collect(space, n=400, seed=0, batch=False):
    domain = Domain(None, space)
    trials = Trials()
    ids = trials.new_trial_ids(n)
    fn = rand.suggest_batch if batch else rand.suggest
    docs = fn(ids, domain, trials, seed)
    return docs, domain


def test_suggest_bounds_and_quantization():
    space = {
        "u": hp.uniform("u", -2, 3),
        "qu": hp.quniform("qu", 0, 10, 2.5),
        "lu": hp.loguniform("lu", -2, 2),
        "ri": hp.randint("ri", 3, 9),
        "ui": hp.uniformint("ui", 1, 4),
    }
    docs, _ = _collect(space)
    u = np.array([d["misc"]["vals"]["u"][0] for d in docs])
    qu = np.array([d["misc"]["vals"]["qu"][0] for d in docs])
    lu = np.array([d["misc"]["vals"]["lu"][0] for d in docs])
    ri = np.array([d["misc"]["vals"]["ri"][0] for d in docs])
    ui = np.array([d["misc"]["vals"]["ui"][0] for d in docs])
    assert u.min() >= -2 and u.max() <= 3
    np.testing.assert_allclose(qu, np.round(qu / 2.5) * 2.5, atol=1e-5)
    assert lu.min() >= np.exp(-2) - 1e-5 and lu.max() <= np.exp(2) + 1e-5
    assert set(np.unique(ri)) <= set(range(3, 9))
    assert set(np.unique(ui)) <= {1, 2, 3, 4}
    # rough uniformity of the uniform draw
    assert abs(u.mean() - 0.5) < 0.3


def test_suggest_conditional_sparsity():
    space = hp.choice("c", [{"x": hp.uniform("x", 0, 1)},
                            {"y": hp.uniform("y", 0, 1)}])
    docs, _ = _collect(space, n=200)
    for d in docs:
        vals = d["misc"]["vals"]
        branch = vals["c"][0]
        if branch == 0:
            assert len(vals["x"]) == 1 and len(vals["y"]) == 0
        else:
            assert len(vals["x"]) == 0 and len(vals["y"]) == 1
    branches = np.array([d["misc"]["vals"]["c"][0] for d in docs])
    assert 0.3 < branches.mean() < 0.7


def test_suggest_batch_matches_serial_distribution():
    space = {"u": hp.uniform("u", 0, 1)}
    serial, _ = _collect(space, n=300, seed=5)
    batch, _ = _collect(space, n=300, seed=5, batch=True)
    a = np.array([d["misc"]["vals"]["u"][0] for d in serial])
    b = np.array([d["misc"]["vals"]["u"][0] for d in batch])
    # same fold_in construction → identical draws
    np.testing.assert_allclose(a, b, atol=1e-6)


def test_pchoice_frequencies():
    space = hp.pchoice("p", [(0.8, "a"), (0.2, "b")])
    docs, _ = _collect(space, n=1000)
    idx = np.array([d["misc"]["vals"]["p"][0] for d in docs])
    assert abs((idx == 0).mean() - 0.8) < 0.06


def test_rand_fmin_on_conditional_space():
    space = hp.choice("c", [
        {"kind": "a", "x": hp.uniform("xa", -5, 5)},
        {"kind": "b", "y": hp.uniform("yb", 0, 1)},
    ])

    def obj(d):
        return (d["x"] - 2) ** 2 if d["kind"] == "a" else 5 + d["y"]

    t = Trials()
    fmin(obj, space, algo=rand.suggest, max_evals=60, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False)
    assert t.best_trial["result"]["loss"] < 5


def test_seed_high_bits_produce_distinct_streams():
    # rstate-derived seeds can exceed 32 bits; truncating them (an earlier
    # bug masked with 0x7FFFFFFF) must not collapse distinct seeds
    import jax

    from hyperopt_tpu.algos.rand import seed_to_key

    lo, hi = 123, 123 + 2**33
    k_lo = np.asarray(jax.random.key_data(seed_to_key(lo)))
    k_hi = np.asarray(jax.random.key_data(seed_to_key(hi)))
    assert not np.array_equal(k_lo, k_hi)

    space = {"u": hp.uniform("u", 0, 1)}
    a = _collect(space, n=8, seed=lo)[0]
    b = _collect(space, n=8, seed=hi)[0]
    va = [d["misc"]["vals"]["u"][0] for d in a]
    vb = [d["misc"]["vals"]["u"][0] for d in b]
    assert va != vb
