"""ISSUE 9: the ask/tell service layer — scheduler, space schema, HTTP.

The scheduler's correctness properties (quotas, eviction/re-admission
invariance, cohort packing, persistence) plus the serving front end's
contract (routes, error mapping, exposition-format lint, concurrent wave
batching).  The heavy determinism pins live in test_batched_suggest.py.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from hyperopt_tpu import hp
from hyperopt_tpu.filestore import FileTrials, new_run_id
from hyperopt_tpu.service import (StudyQuotaError, StudyScheduler,
                                  UnknownStudyError, space_from_spec)
from hyperopt_tpu.service.scheduler import DuplicateTellError
from hyperopt_tpu.service.server import ServiceHTTPServer
from hyperopt_tpu.service.spacespec import SpaceSpecError
from hyperopt_tpu.zoo import ZOO, make_study_mix

SPACE = {"x": hp.uniform("x", -5, 5)}


def _loss(params):
    return float((params["x"] - 2.0) ** 2)


def _drive(sched, sid, n_iters, n=1):
    out = []
    for _ in range(n_iters):
        for a in sched.ask(sid, n):
            sched.tell(sid, a["tid"], _loss(a["params"]))
            out.append(a["params"])
    return out


# ---------------------------------------------------------------------------
# scheduler basics
# ---------------------------------------------------------------------------


def test_create_ask_tell_flow():
    sched = StudyScheduler()
    sid = sched.create_study(SPACE, seed=5, n_startup_jobs=3)
    assert sid.startswith("study-")
    params = _drive(sched, sid, 8)
    assert len(params) == 8
    st = sched.study_status(sid)
    assert st["n_trials"] == 8 and st["n_pending"] == 0
    assert st["best_loss"] is not None


def test_run_id_opaque_and_unique():
    ids = {new_run_id("study") for _ in range(64)}
    assert len(ids) == 64
    assert all(i.startswith("study-") for i in ids)


def test_quota_max_studies():
    sched = StudyScheduler(max_studies=2)
    sched.create_study(SPACE, seed=0)
    sched.create_study(SPACE, seed=1)
    with pytest.raises(StudyQuotaError):
        sched.create_study(SPACE, seed=2)
    # closing one frees the quota
    sched.close_study(sched.studies_status()["studies"][0]["study_id"])
    sched.create_study(SPACE, seed=3)


def test_quota_max_pending():
    sched = StudyScheduler(max_pending=3)
    sid = sched.create_study(SPACE, seed=0, n_startup_jobs=1)
    asked = sched.ask(sid, 3)
    with pytest.raises(StudyQuotaError):
        sched.ask(sid, 1)
    sched.tell(sid, asked[0]["tid"], 1.0)
    sched.ask(sid, 1)  # freed


def test_budget_marks_study_done():
    sched = StudyScheduler()
    sid = sched.create_study(SPACE, seed=0, n_startup_jobs=2, max_trials=4)
    _drive(sched, sid, 4)
    assert sched.study_status(sid)["state"] == "done"
    with pytest.raises((StudyQuotaError, UnknownStudyError)):
        sched.ask(sid, 1)


def test_unknown_study_and_double_tell():
    sched = StudyScheduler()
    with pytest.raises(UnknownStudyError):
        sched.ask("study-nope")
    sid = sched.create_study(SPACE, seed=0, n_startup_jobs=1)
    a = sched.ask(sid)[0]
    sched.tell(sid, a["tid"], 0.5)
    with pytest.raises(DuplicateTellError):
        sched.tell(sid, a["tid"], 0.5)
    with pytest.raises(UnknownStudyError):
        sched.tell(sid, 10**6, 0.5)


def test_failed_trial_tell():
    sched = StudyScheduler()
    sid = sched.create_study(SPACE, seed=0, n_startup_jobs=1)
    a = sched.ask(sid)[0]
    sched.tell(sid, a["tid"], loss=None)  # no loss -> STATUS_FAIL
    st = sched.study_status(sid)
    assert st["n_trials"] == 1 and st["best_loss"] is None
    # the failed trial never poisons later asks
    _drive(sched, sid, 3)


def test_tell_nonfinite_loss_records_fail_even_with_ok_status():
    """status='ok' never overrides the finite-loss guard: an inf/NaN loss
    settles as STATUS_FAIL instead of poisoning the posterior."""
    sched = StudyScheduler()
    sid = sched.create_study(SPACE, seed=0, n_startup_jobs=1)
    asked = sched.ask(sid, 3)
    sched.tell(sid, asked[0]["tid"], loss=float("inf"), status="ok")
    sched.tell(sid, asked[1]["tid"], loss=float("nan"))
    sched.tell(sid, asked[2]["tid"], loss=None, status="ok")
    st = sched._studies[sid]
    assert [r["status"] for r in st.trials.results] == ["fail"] * 3
    assert sched.study_status(sid)["best_loss"] is None
    _drive(sched, sid, 2)  # posterior still healthy


def test_empty_cohorts_are_garbage_collected():
    sched = StudyScheduler()
    sid = sched.create_study(SPACE, seed=3, n_startup_jobs=2, max_trials=6)
    _drive(sched, sid, 6)  # budget done -> evicted from its cohort
    assert sched.study_status(sid)["state"] == "done"
    sched._gc_cohorts()
    assert not sched._cohorts  # no live slots -> no pinned device stacks


def test_eviction_and_bit_identical_readmission():
    """Evicting an idle study's slot and re-admitting it from the host
    arrays must not perturb its proposal stream: compare against an
    uninterrupted twin."""
    def run(evict_mid):
        sched = StudyScheduler()
        sid = sched.create_study(SPACE, seed=17, n_startup_jobs=2)
        out = []
        for i in range(10):
            if evict_mid and i == 6:
                sched._evict_from_cohort(sched._studies[sid])
            out.extend(_drive(sched, sid, 1))
        return out

    assert run(True) == run(False)


def test_evict_idle_frees_slots():
    sched = StudyScheduler(idle_sec=0.5)
    sid = sched.create_study(SPACE, seed=0, n_startup_jobs=1)
    _drive(sched, sid, 3)
    assert sum(c.n_live for c in sched._cohorts.values()) == 1
    sched.evict_idle(now=sched._studies[sid].last_active + 1.0)
    assert sum(c.n_live for c in sched._cohorts.values()) == 0
    _drive(sched, sid, 1)  # next ask re-admits


def test_idle_sec_zero_means_never_evict():
    sched = StudyScheduler(idle_sec=0)
    sid = sched.create_study(SPACE, seed=0, n_startup_jobs=1)
    _drive(sched, sid, 2)
    sched.evict_idle(now=sched._studies[sid].last_active + 1e9)
    assert sum(c.n_live for c in sched._cohorts.values()) == 1


def test_wave_batches_one_tick_per_cohort():
    sched = StudyScheduler()
    sids = [sched.create_study(SPACE, seed=i, n_startup_jobs=1)
            for i in range(6)]
    # graduate everyone to TPE
    answers = sched.ask_many([(sid, 1) for sid in sids])
    for sid in sids:
        for a in answers[sid]:
            sched.tell(sid, a["tid"], _loss(a["params"]))
    ticks0 = sched.metrics.counter("service.ticks").value
    answers = sched.ask_many([(sid, 1) for sid in sids])
    assert sum(len(v) for v in answers.values()) == 6
    assert sched.metrics.counter("service.ticks").value == ticks0 + 1
    assert 0.0 < sched.slot_utilization() <= 1.0


def test_filestore_persistence_round_trip(tmp_path):
    sched = StudyScheduler(store_root=str(tmp_path))
    sid = sched.create_study(SPACE, seed=11, n_startup_jobs=3)
    _drive(sched, sid, 7)
    t2 = FileTrials(str(tmp_path / sid))
    assert len(t2.trials) == 7
    assert all(d["result"].get("loss") is not None for d in t2.trials)
    # tell settled the docs: no stale new/ copies left behind
    assert not any(p.name.endswith(".pkl")
                   for p in (tmp_path / sid / "new").iterdir())


# ---------------------------------------------------------------------------
# space schema
# ---------------------------------------------------------------------------


def test_space_from_spec_families():
    spec = {
        "u": {"dist": "uniform", "args": [-1, 1]},
        "qu": {"dist": "quniform", "args": [0, 10, 2]},
        "ui": {"dist": "uniformint", "args": [1, 8]},
        "lu": {"dist": "loguniform", "args": [-3, 0]},
        "qlu": {"dist": "qloguniform", "args": [0, 3, 1]},
        "n": {"dist": "normal", "args": [0, 1]},
        "qn": {"dist": "qnormal", "args": [0, 1, 0.5]},
        "ln": {"dist": "lognormal", "args": [0, 1]},
        "qln": {"dist": "qlognormal", "args": [0, 1, 1]},
        "ri": {"dist": "randint", "args": [5]},
        "c": {"dist": "choice", "options": [0, 1, 2]},
        "pc": {"dist": "pchoice", "options": [[0.2, 0], [0.8, 1]]},
    }
    space = space_from_spec(spec)
    sched = StudyScheduler()
    sid = sched.create_study(space, seed=1, n_startup_jobs=2)
    params = _drive_any(sched, sid, 4)
    assert len(params) == 4


def _drive_any(sched, sid, n_iters):
    out = []
    for _ in range(n_iters):
        for a in sched.ask(sid, 1):
            loss = float(sum(float(v) for v in a["params"].values()))
            sched.tell(sid, a["tid"], loss)
            out.append(a["params"])
    return out


def test_space_from_spec_nested_choice():
    spec = {"head": {"dist": "choice",
                     "options": [{"w": {"dist": "uniform", "args": [0, 1]}},
                                 "flat"]}}
    space = space_from_spec(spec)
    sched = StudyScheduler()
    sid = sched.create_study(space, seed=2, n_startup_jobs=2)
    assert len(_drive_any(sched, sid, 3)) == 3


def test_space_from_spec_errors():
    with pytest.raises(SpaceSpecError):
        space_from_spec({})
    with pytest.raises(SpaceSpecError):
        space_from_spec({"x": {"dist": "warp", "args": [1]}})
    with pytest.raises(SpaceSpecError):
        space_from_spec({"x": {"dist": "uniform", "args": [1]}})  # arity
    with pytest.raises(SpaceSpecError):
        space_from_spec({"x": {"dist": "choice", "options": []}})
    with pytest.raises(SpaceSpecError):
        space_from_spec({"x": "not-a-node"})


# ---------------------------------------------------------------------------
# the study mix (standing multi-study workload)
# ---------------------------------------------------------------------------


def test_make_study_mix_shape_and_determinism():
    mix = make_study_mix(12)
    assert len(mix) == 12
    assert mix == make_study_mix(12)
    # heterogeneous: several distinct spaces and budgets
    assert len({m.domain.name for m in mix}) >= 3
    assert len({m.budget for m in mix}) >= 2
    assert all(m.domain is ZOO[m.domain.name] for m in mix)
    assert [m.seed for m in mix] == list(range(12))


def test_study_mix_drives_through_scheduler():
    mix = make_study_mix(6)
    sched = StudyScheduler()
    sids = [sched.create_study(m.domain.space, seed=m.seed,
                               n_startup_jobs=2) for m in mix]
    for _ in range(4):
        answers = sched.ask_many([(sid, 1) for sid in sids])
        for sid, m in zip(sids, mix):
            for a in answers[sid]:
                sched.tell(sid, a["tid"],
                           float(sum(float(v) for v in a["params"].values())))
    status = sched.studies_status()
    assert status["n_studies"] == 6
    assert len(status["cohorts"]) >= 3  # heterogeneous spaces -> cohorts


# ---------------------------------------------------------------------------
# HTTP front end
# ---------------------------------------------------------------------------


def test_handle_routes_without_socket():
    srv = ServiceHTTPServer(0)
    code, r = srv.handle("POST", "/study",
                         {"space": {"x": {"dist": "uniform",
                                          "args": [-5, 5]}},
                          "seed": 3, "n_startup_jobs": 2})
    assert code == 200 and r["ok"]
    sid = r["study_id"]
    code, r = srv.handle("POST", "/ask", {"study_id": sid, "n": 2})
    assert code == 200 and len(r["trials"]) == 2
    code, r = srv.handle("POST", "/tell", {
        "study_id": sid,
        "results": [{"tid": t["tid"], "loss": 1.0} for t in r["trials"]]})
    assert code == 200 and r["told"] == 2
    code, r = srv.handle("GET", "/studies", {})
    assert code == 200 and r["n_studies"] == 1
    code, r = srv.handle("GET", "/snapshot", {})
    assert code == 200 and "service" in r["sections"]
    code, r = srv.handle("POST", "/close", {"study_id": sid})
    assert code == 200


def test_handle_error_mapping():
    srv = ServiceHTTPServer(0)
    assert srv.handle("POST", "/ask", {"study_id": "study-x"})[0] == 404
    assert srv.handle("POST", "/study", {})[0] == 400
    # double tell answers 409 (permanent conflict), never a retryable 429
    code, r = srv.handle("POST", "/study",
                         {"space": {"x": {"dist": "uniform",
                                          "args": [0, 1]}},
                          "n_startup_jobs": 1})
    sid = r["study_id"]
    tid = srv.handle("POST", "/ask", {"study_id": sid})[1]["trials"][0]["tid"]
    assert srv.handle("POST", "/tell", {"study_id": sid, "tid": tid,
                                        "loss": 0.1})[0] == 200
    assert srv.handle("POST", "/tell", {"study_id": sid, "tid": tid,
                                        "loss": 0.1})[0] == 409
    # a retried BATCH skips already-told tids instead of stranding the rest
    tid2 = srv.handle("POST", "/ask",
                      {"study_id": sid})[1]["trials"][0]["tid"]
    code, r = srv.handle("POST", "/tell", {
        "study_id": sid,
        "results": [{"tid": tid, "loss": 0.1}, {"tid": tid2, "loss": 0.2}]})
    assert code == 200 and r["told"] == 1 and r["duplicates"] == 1
    assert srv.handle("POST", "/tell", {"study_id": sid,
                                        "results": ["junk"]})[0] == 400
    assert srv.handle("POST", "/study",
                      {"space": {"x": {"dist": "bogus"}}})[0] == 400
    assert srv.handle("POST", "/study", {"zoo": "not-a-domain"})[0] == 400
    assert srv.handle("GET", "/nope", {})[0] == 404
    assert srv.handle("PUT", "/ask", {})[0] == 405
    srv2 = ServiceHTTPServer(0, scheduler=StudyScheduler(max_studies=0))
    assert srv2.handle("POST", "/study",
                       {"space": {"x": {"dist": "uniform",
                                        "args": [0, 1]}}})[0] == 429


def test_handle_zoo_study():
    srv = ServiceHTTPServer(0)
    code, r = srv.handle("POST", "/study",
                         {"zoo": "branin", "n_startup_jobs": 2})
    assert code == 200
    code, r = srv.handle("POST", "/ask", {"study_id": r["study_id"]})
    assert code == 200 and set(r["trials"][0]["params"]) == {"x", "y"}


def _post(url, path, body):
    req = urllib.request.Request(
        url + path, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_real_http_concurrent_studies():
    """Real sockets, concurrent clients: wave batching serves everyone,
    /metrics passes the exposition lint, /studies reflects the drive."""
    srv = ServiceHTTPServer(0)
    assert srv.start()
    url = srv.url
    try:
        errors = []

        def drive(tag):
            try:
                code, r = _post(url, "/study", {
                    "space": {"x": {"dist": "uniform", "args": [-5, 5]}},
                    "seed": tag, "n_startup_jobs": 2})
                assert code == 200, r
                sid = r["study_id"]
                for _ in range(5):
                    code, a = _post(url, "/ask", {"study_id": sid})
                    assert code == 200, a
                    t = a["trials"][0]
                    code, _r = _post(url, "/tell", {
                        "study_id": sid, "tid": t["tid"],
                        "loss": (t["params"]["x"] - 1) ** 2})
                    assert code == 200, _r
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=drive, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors

        with urllib.request.urlopen(url + "/studies", timeout=30) as resp:
            studies = json.loads(resp.read())
        assert studies["n_studies"] == 8
        assert all(s["n_trials"] == 5 for s in studies["studies"])

        with urllib.request.urlopen(url + "/metrics", timeout=30) as resp:
            text = resp.read().decode()
        assert "hyperopt_tpu_service_asks_total" in text
        import os
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "scripts"))
        from validate_scrape import validate_metrics_text

        assert validate_metrics_text(text) == []
    finally:
        srv.stop()


def test_server_fail_open_on_taken_port():
    srv = ServiceHTTPServer(0)
    assert srv.start()
    try:
        port = int(srv.url.rsplit(":", 1)[1])
        srv2 = ServiceHTTPServer(port)
        assert srv2.start() is False  # warns, never raises
    finally:
        srv.stop()


def test_env_knob_parsing():
    from hyperopt_tpu._env import (parse_service, parse_service_idle_sec,
                                   parse_service_max_pending,
                                   parse_service_max_studies)

    assert parse_service({}) is None
    assert parse_service({"HYPEROPT_TPU_SERVICE": "0"}) is None
    assert parse_service({"HYPEROPT_TPU_SERVICE": "9200"}) == 9200
    assert parse_service(
        {"HYPEROPT_TPU_SERVICE": "0.0.0.0:9200"}) == "0.0.0.0:9200"
    assert parse_service({"HYPEROPT_TPU_SERVICE": "soon"}) is None
    assert parse_service_max_studies({}) == 4096
    assert parse_service_max_studies(
        {"HYPEROPT_TPU_SERVICE_MAX_STUDIES": "7"}) == 7
    assert parse_service_max_pending({}) == 64
    assert parse_service_idle_sec(
        {"HYPEROPT_TPU_SERVICE_IDLE_SEC": "30"}) == 30.0
    assert parse_service_idle_sec(
        {"HYPEROPT_TPU_SERVICE_IDLE_SEC": "0.5"}) == 0.5  # fractions, CLI-like
    assert parse_service_idle_sec(
        {"HYPEROPT_TPU_SERVICE_IDLE_SEC": "0"}) == float("inf")  # disabled
    assert parse_service_idle_sec(
        {"HYPEROPT_TPU_SERVICE_IDLE_SEC": "soon"}) == 600.0  # warn+default


# ---------------------------------------------------------------------------
# spacespec robustness (ISSUE 10 satellite): hostile schemas answer 400,
# never 500
# ---------------------------------------------------------------------------


def _deep_choice_spec(depth):
    node = {"dist": "uniform", "args": [0, 1]}
    spec = {"leaf": node}
    for i in range(depth):
        spec = {f"c{i}": {"dist": "choice", "options": [spec, 0]}}
    return spec


def _hostile_specs():
    """Fuzz-style corpus: every shape a confused or hostile client can
    put on the wire (plus Python-API-only shapes like cyclic dicts)."""
    cyclic = {"x": {"dist": "choice", "options": []}}
    cyclic["x"]["options"].append(cyclic)  # truly cyclic via options
    huge_label = "x" * 10_000
    return [
        None,
        [],
        "a string",
        42,
        {},                                        # empty mapping
        {"x": None},
        {"x": []},
        {"x": "not-a-node"},
        {"x": {}},                                 # no dist
        {"x": {"dist": None}},
        {"x": {"dist": 7}},                        # non-string family
        {"x": {"dist": "warp", "args": [1]}},      # unknown family
        {"x": {"dist": "uniform"}},                # missing args
        {"x": {"dist": "uniform", "args": "ab"}},
        {"x": {"dist": "uniform", "args": [1]}},   # arity
        {"x": {"dist": "uniform", "args": [1, 2, 3, 4]}},
        {"x": {"dist": "uniform", "args": [None, 2]}},
        {"x": {"dist": "uniform", "args": ["a", "b"]}},
        {"x": {"dist": "choice"}},                 # no options
        {"x": {"dist": "choice", "options": []}},
        {"x": {"dist": "choice", "options": "ab"}},
        {"x": {"dist": "choice", "options": [["nested", "list"]]}},
        {"x": {"dist": "choice",
               "options": [{"dist": "uniform", "args": [0, 1]}]}},
        {"x": {"dist": "pchoice", "options": [0, 1]}},  # not pairs
        {"x": {"dist": "pchoice", "options": [["p", 0]]}},
        {huge_label: {"dist": "uniform", "args": [0, 1]}},  # label len
        {"": {"dist": "uniform", "args": [0, 1]}},          # empty label
        {"x": {"dist": "choice",
               "options": list(range(5000))}},     # huge option list
        _deep_choice_spec(64),                     # over-deep nesting
        cyclic,                                    # cyclic (API-only)
        {f"p{i}": {"dist": "uniform", "args": [0, 1]}
         for i in range(1000)},                    # too many params
    ]


def test_spacespec_fuzz_raises_typed_errors():
    for spec in _hostile_specs():
        with pytest.raises(SpaceSpecError):
            space_from_spec(spec)


def test_spacespec_fuzz_answers_400_never_500():
    server = ServiceHTTPServer(0)
    for spec in _hostile_specs():
        code, payload = server.handle("POST", "/study", {"space": spec})
        assert code == 400, (code, payload, spec if not isinstance(
            spec, dict) or len(spec) < 5 else "large spec")
        assert payload["ok"] is False and payload["error"]


def test_spacespec_limits_leave_sane_specs_alone():
    from hyperopt_tpu.service.spacespec import MAX_DEPTH

    space = space_from_spec(_deep_choice_spec(MAX_DEPTH - 2))
    assert space  # deep-but-legal still builds
    labels = {f"p{i}": {"dist": "uniform", "args": [0, 1]}
              for i in range(64)}
    assert space_from_spec(labels)


def test_non_string_label_rejected():
    with pytest.raises(SpaceSpecError):
        space_from_spec({7: {"dist": "uniform", "args": [0, 1]}})


# ---------------------------------------------------------------------------
# ServiceClient retry/backoff (ISSUE 10 satellite)
# ---------------------------------------------------------------------------


def test_client_honors_retry_after_and_conn_resets(monkeypatch):
    from hyperopt_tpu.retry import RetryPolicy
    from hyperopt_tpu.service.client import ServiceClient, ServiceUnavailable

    sleeps = []
    client = ServiceClient("http://127.0.0.1:1", sleep=sleeps.append,
                           retry=RetryPolicy(max_retries=4, base_delay=0.1,
                                             max_delay=2.0, jitter=0.5))
    script = [
        (429, {"ok": False, "error": "shed"}, "0.8"),
        ConnectionResetError("mid-restart"),
        (503, {"ok": False, "error": "draining", "retry_after": 0.3}, "0.3"),
        (200, {"ok": True, "study_id": "s1"}, None),
    ]

    def fake_once(method, path, body):
        step = script.pop(0)
        if isinstance(step, Exception):
            raise step
        return step

    monkeypatch.setattr(client, "_once", fake_once)
    status, payload = client.request("POST", "/study", {})
    assert status == 200 and payload["study_id"] == "s1"
    assert len(sleeps) == 3 and client.retries == 3
    # Retry-After floors the first backoff (0.8 > base jittered delay)
    assert sleeps[0] >= 0.8
    # deterministic jitter: replaying the schedule gives the same sleeps
    sleeps2 = []
    client2 = ServiceClient("http://127.0.0.1:1", sleep=sleeps2.append,
                            retry=RetryPolicy(max_retries=4, base_delay=0.1,
                                              max_delay=2.0, jitter=0.5))
    script[:] = [
        (429, {"ok": False, "error": "shed"}, "0.8"),
        ConnectionResetError("mid-restart"),
        (503, {"ok": False, "error": "draining", "retry_after": 0.3}, "0.3"),
        (200, {"ok": True, "study_id": "s1"}, None),
    ]
    monkeypatch.setattr(client2, "_once", fake_once)
    client2.request("POST", "/study", {})
    assert sleeps == sleeps2


def test_client_exhausts_retries(monkeypatch):
    from hyperopt_tpu.retry import RetryPolicy
    from hyperopt_tpu.service.client import ServiceClient, ServiceUnavailable

    client = ServiceClient("http://127.0.0.1:1", sleep=lambda _s: None,
                           retry=RetryPolicy(max_retries=2, base_delay=0.01))
    monkeypatch.setattr(
        client, "_once",
        lambda *a: (429, {"ok": False, "error": "shed"}, "0.1"))
    with pytest.raises(ServiceUnavailable) as ei:
        client.request("POST", "/ask", {})
    assert ei.value.status == 429


def test_client_tell_409_is_success(monkeypatch):
    from hyperopt_tpu.service.client import ServiceClient

    client = ServiceClient("http://127.0.0.1:1", sleep=lambda _s: None)
    monkeypatch.setattr(
        client, "_once",
        lambda *a: (409, {"ok": False, "error": "already told"}, None))
    assert client.tell("s1", 3, 0.5) == {"duplicate": True}


def test_client_does_not_retry_permanent_errors(monkeypatch):
    from hyperopt_tpu.service.client import ServiceClient

    calls = []

    def fake_once(method, path, body):
        calls.append(path)
        return 404, {"ok": False, "error": "no such study"}, None

    client = ServiceClient("http://127.0.0.1:1", sleep=lambda _s: None)
    monkeypatch.setattr(client, "_once", fake_once)
    status, payload = client.request("POST", "/ask", {})
    assert status == 404 and len(calls) == 1


def test_issue10_env_knob_parsing():
    from hyperopt_tpu._env import (parse_service_deadline_ms,
                                   parse_service_degrade,
                                   parse_service_queue,
                                   parse_service_wal)

    assert parse_service_wal({}) == "auto"
    assert parse_service_wal({"HYPEROPT_TPU_SERVICE_WAL": "on"}) == "auto"
    assert parse_service_wal({"HYPEROPT_TPU_SERVICE_WAL": "off"}) is None
    assert parse_service_wal({"HYPEROPT_TPU_SERVICE_WAL": "0"}) is None
    assert parse_service_wal(
        {"HYPEROPT_TPU_SERVICE_WAL": "/tmp/x.jsonl"}) == "/tmp/x.jsonl"
    assert parse_service_deadline_ms({}) == 30000.0
    assert parse_service_deadline_ms(
        {"HYPEROPT_TPU_SERVICE_DEADLINE_MS": "off"}) is None
    assert parse_service_deadline_ms(
        {"HYPEROPT_TPU_SERVICE_DEADLINE_MS": "1500"}) == 1500.0
    assert parse_service_deadline_ms(
        {"HYPEROPT_TPU_SERVICE_DEADLINE_MS": "soon"}) == 30000.0
    assert parse_service_queue({}) == 256
    assert parse_service_queue({"HYPEROPT_TPU_SERVICE_QUEUE": "8"}) == 8
    assert parse_service_queue({"HYPEROPT_TPU_SERVICE_QUEUE": "-1"}) == 256
    assert parse_service_degrade({}) == 8
    assert parse_service_degrade(
        {"HYPEROPT_TPU_SERVICE_DEGRADE": "off"}) is None
    assert parse_service_degrade(
        {"HYPEROPT_TPU_SERVICE_DEGRADE": "3"}) == 3
    assert parse_service_degrade(
        {"HYPEROPT_TPU_SERVICE_DEGRADE": "1"}) == 1  # fastest recovery
    assert parse_service_degrade(
        {"HYPEROPT_TPU_SERVICE_DEGRADE": "soon"}) == 8
