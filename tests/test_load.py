"""ISSUE 17: the load & cost-attribution observatory.

The acceptance pins:

* K-row share attribution is exact arithmetic: each study in a cohort
  tick is charged ``k_i / sum(k)`` of the measured device time (and the
  candidate/HBM estimates), so per-study rows sum to the scheduler
  totals to the float;
* armed attribution NEVER changes proposals: armed == disarmed
  bit-identical, directly and over HTTP — and disarmed really is
  ``scheduler.load is None``: zero threads, zero allocations traced to
  the ledger module on the serving path;
* the durable heat ledger survives SIGKILL (complete lines parse, a
  torn tail is classified TORN and skipped silently, a bit-flip is
  CORRUPT and skipped loudly) and migration adoption INHERITS the
  shard's accumulated heat — a shard doesn't cool off by moving;
* the steward's volunteer handoff releases the HOTTEST held shard
  first (pure ordering change; disarmed ties reproduce the old
  highest-shard pick);
* the ``imbalance`` SLO objective burns budget on skew breaches, and
  the new bench keys really gate: ``attribution_overhead_frac``
  absolute from the first record, ``shard_heat_skew`` windowed
  lower-is-better.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import tracemalloc

import pytest

from hyperopt_tpu import hp
from hyperopt_tpu._env import parse_load, parse_load_slo
from hyperopt_tpu.obs.load import (
    CostLedger,
    HeatLedger,
    heat_path_for,
    heat_skew,
    inherited_heat,
    merge_status,
    read_heat,
)
from hyperopt_tpu.obs.slo import LOAD_TARGETS, SLOPlane
from hyperopt_tpu.service import FleetReplica
from hyperopt_tpu.service.scheduler import StudyScheduler
from hyperopt_tpu.service.server import ServiceHTTPServer

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))

SPACE = {"x": hp.uniform("x", -5, 5)}
SPACE_SPEC = {"x": {"dist": "uniform", "args": [-5, 5]}}


# ---------------------------------------------------------------------------
# attribution math: hand-computed K-row shares
# ---------------------------------------------------------------------------


def test_tick_attribution_matches_hand_computed_shares():
    led = CostLedger()
    # one 4 ms tick, studies a/b/c asking 2/1/1 rows of the 4
    led.observe_tick([("a", 2), ("b", 1), ("c", 1)], device_sec=0.004,
                     cand=96.0, hbm_bytes=400.0, cohort="cap16")
    a = led.study_status("a")
    assert a["device_ms"] == pytest.approx(2.0)       # 2/4 of 4 ms
    assert a["asks"] == 2 and a["waves"] == 1
    assert a["cand"] == pytest.approx(48.0)           # 2/4 of 96
    assert a["hbm_bytes"] == pytest.approx(200.0)
    assert a["cohort"] == "cap16"
    b = led.study_status("b")
    assert b["device_ms"] == pytest.approx(1.0)
    assert b["cand"] == pytest.approx(24.0)
    # shares sum EXACTLY to the measured tick
    assert led.device_ms == pytest.approx(4.0)
    assert led.asks == 4 and led.waves == 1
    # second tick, only a: its EWMA folds (alpha=0.3 default)
    led.observe_tick([("a", 1)], device_sec=0.001)
    a2 = led.study_status("a")
    assert a2["device_ms"] == pytest.approx(3.0)
    assert a2["ewma_ms"] == pytest.approx(0.3 * 1.0 + 0.7 * (0.3 * 2.0))
    # tells ride separately (the tell path has no wave)
    led.observe_tell("a")
    led.observe_tell("zz")                            # admits a row
    assert led.study_status("a")["tells"] == 1
    assert led.study_status("zz")["asks"] == 0
    assert led.tells == 2
    st = led.status()
    assert st["studies"] == 4
    assert st["cohorts"]["cap16"]["studies"] == 3
    assert st["cohorts"]["unticked"]["studies"] == 1  # zz: told, never ticked
    # zero-K ticks are ignored, forget drops the row
    led.observe_tick([], device_sec=1.0)
    assert led.waves == 2
    led.forget("zz")
    assert led.study_status("zz") is None


def test_heat_inheritance_is_idempotent_max():
    led = CostLedger()
    led.observe_tick([("a", 1)], device_sec=0.002)
    assert led.heat_ms == pytest.approx(2.0)
    led.inherit(100.0)
    led.inherit(50.0)        # a smaller re-adoption never shrinks heat
    led.inherit(100.0)       # nor does a repeat double it
    assert led.inherited_ms == 100.0
    assert led.heat_ms == pytest.approx(102.0)
    rec = led.heat_record()
    assert rec["kind"] == "heat" and rec["heat_ms"] == pytest.approx(102.0)
    json.dumps(rec)          # ledger rows must serialize


def test_heat_skew_and_merge_status():
    assert heat_skew([]) == 1.0
    assert heat_skew([5.0]) == 1.0                    # one shard: balanced
    assert heat_skew([0.0, 0.0]) == 1.0               # idle fleet: balanced
    assert heat_skew([9.0, 1.0, 2.0]) == pytest.approx(9.0 / 4.0)
    assert merge_status([]) is None
    a, b = CostLedger(), CostLedger()
    a.bind(shard=0, replica="r")
    b.bind(shard=1, replica="r")
    a.observe_tick([("s0", 3)], device_sec=0.009)
    b.observe_tick([("s1", 1)], device_sec=0.003)
    b.observe_tell("s1")
    m = merge_status([a.status(), b.status(), None])
    assert m["studies"] == 2 and m["asks"] == 4 and m["tells"] == 1
    assert m["device_ms"] == pytest.approx(12.0)
    assert m["shards"]["0"]["heat_ms"] == pytest.approx(9.0)
    assert m["heat_skew"] == pytest.approx(9.0 / 6.0)


def test_gauges_publish_only_when_bound():
    from hyperopt_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    led = CostLedger(metrics=reg)
    led.observe_tick([("a", 1)], device_sec=0.001)
    led.publish()                                     # unbound: no gauges
    assert not any(n.startswith("service.load.shard.")
                   for n in reg.snapshot()["metrics"])
    led.bind(shard=3, replica="r")
    st = led.publish()
    snap = reg.snapshot()["metrics"]
    assert snap["service.load.shard.3.heat_ms"] == st["heat_ms"]
    assert snap["service.load.shard.3.waves"] == 1


# ---------------------------------------------------------------------------
# armed == disarmed: attribution never changes proposals
# ---------------------------------------------------------------------------


def _drive(sched, sid, n):
    out = []
    for _ in range(n):
        a = sched.ask(sid)[0]
        out.append((a["tid"], repr(a["params"]["x"])))
        sched.tell(sid, a["tid"], float((a["params"]["x"] - 1.0) ** 2))
    return out


def test_armed_equals_disarmed_bit_identical():
    on = StudyScheduler(wal=False, quality=False, load=CostLedger())
    off = StudyScheduler(wal=False, quality=False, load=False)
    assert on.load is not None and off.load is None
    sid_on = on.create_study(SPACE, seed=21, n_startup_jobs=2)
    sid_off = off.create_study(SPACE, seed=21, n_startup_jobs=2)
    assert _drive(on, sid_on, 8) == _drive(off, sid_off, 8)
    # the armed run really attributed: device waves happened past startup
    c = on.load.study_status(sid_on)
    assert c is not None and c["tells"] == 8
    assert c["waves"] >= 1 and c["device_ms"] > 0.0


def test_armed_equals_disarmed_over_http():
    def drive(srv, sid, n):
        seq = []
        waves = []
        for _ in range(n):
            code, a = srv.handle("POST", "/ask", {"study_id": sid})
            assert code == 200
            t = a["trials"][0]
            seq.append((t["tid"], repr(t["params"]["x"])))
            if a.get("wave") is not None:
                waves.append(a["wave"])
                assert "wave" not in t     # top-level field, not a trial key
            code, _ = srv.handle("POST", "/tell", {
                "study_id": sid, "tid": t["tid"],
                "loss": float((t["params"]["x"] - 1.0) ** 2)})
            assert code == 200
        return seq, waves

    seqs = {}
    for armed in (True, False):
        sched = StudyScheduler(wal=False, quality=False,
                               load=CostLedger() if armed else False)
        srv = ServiceHTTPServer(0, scheduler=sched, slo=armed, trace=False)
        code, r = srv.handle("POST", "/study", {
            "space": SPACE_SPEC, "seed": 33, "n_startup_jobs": 2})
        seqs[armed], waves = drive(srv, r["study_id"], 8)
        # the wave correlation field (access-log satellite) rides both
        # sides — it comes from the scheduler's wave counter, not the
        # cost plane
        assert waves and waves == sorted(waves)
        if armed:
            snap = srv.snapshot_dict()
            assert snap["load"]["studies"] == 1
            assert snap["load"]["device_ms"] > 0.0
            assert snap["studies"][0]["load"]["tells"] == 8
            code, fl = srv.handle("GET", "/fleet/load", None)
            assert code == 200
            assert fl["local"]["studies"] == 1
        else:
            assert "load" not in srv.snapshot_dict()
    assert seqs[True] == seqs[False]


def test_disarmed_is_none_no_threads_no_ledger_allocations():
    n0 = threading.active_count()
    sched = StudyScheduler(wal=False, quality=False, load=False)
    assert sched.load is None
    sid = sched.create_study(SPACE, seed=9, n_startup_jobs=2)
    _drive(sched, sid, 3)                  # compile outside the trace
    load_py = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "hyperopt_tpu", "obs", "load.py")
    tracemalloc.start()
    try:
        _drive(sched, sid, 3)              # device waves, disarmed
        snap = tracemalloc.take_snapshot()
    finally:
        tracemalloc.stop()
    stats = snap.filter_traces(
        [tracemalloc.Filter(True, load_py)]).statistics("filename")
    assert stats == []                     # zero allocations from the ledger
    # and the armed plane spawns no threads either
    CostLedger().observe_tick([("a", 1)], device_sec=0.001)
    assert threading.active_count() == n0


def test_load_fault_never_fails_the_wave_or_tell():
    sched = StudyScheduler(wal=False, quality=False, load=CostLedger())

    def boom(*a, **kw):
        raise RuntimeError("ledger exploded")

    sched.load.observe_tick = boom
    sched.load.observe_tell = boom
    sid = sched.create_study(SPACE, seed=2, n_startup_jobs=1)
    seq = _drive(sched, sid, 3)            # asks past startup: device waves
    assert len(seq) == 3
    assert sched._studies[sid].best_loss() is not None


# ---------------------------------------------------------------------------
# the durable heat ledger: SIGKILL survival, classification, inheritance
# ---------------------------------------------------------------------------


def test_heat_ledger_roundtrip_and_corruption_classification(tmp_path):
    root = str(tmp_path)
    led = HeatLedger(heat_path_for(root, "rep-a"))
    for i, h in enumerate((10.0, 25.0, 40.0)):
        led.append({"kind": "heat", "replica": "rep-a", "shard": 0,
                    "heat_ms": h, "busy_frac": 0.5, "ts": 100.0 + i})
    HeatLedger(heat_path_for(root, "rep-b")).append(
        {"kind": "heat", "replica": "rep-b", "shard": 1,
         "heat_ms": 5.0, "busy_frac": 0.1, "ts": 200.0})
    m = read_heat(root)
    assert m["files"] == 2 and m["corrupt"] == 0 and m["torn"] == 0
    # cumulative snapshots: merged heat is the MAX, not the sum
    assert m["shards"]["0"]["heat_ms"] == 40.0
    assert m["shards"]["1"]["heat_ms"] == 5.0
    assert m["replicas"]["rep-a"]["busy_frac"] == 0.5
    assert m["heat_skew"] == pytest.approx(40.0 / 22.5, abs=1e-3)
    assert inherited_heat(root, 0) == 40.0
    assert inherited_heat(root, 7) == 0.0             # never-heated shard

    # bit-flip a sealed mid-file record → CORRUPT, skipped, others kept
    pa = heat_path_for(root, "rep-a")
    lines = open(pa, "rb").read().splitlines(keepends=True)
    lines[2] = lines[2].replace(b"40.0", b"41.0", 1)  # breaks the CRC
    open(pa, "wb").write(b"".join(lines))
    # and a torn final line (the SIGKILL-mid-write artifact) → TORN
    with open(pa, "ab") as f:
        f.write(b'{"kind": "heat", "sha')
    m = read_heat(root)
    assert m["corrupt"] == 1 and m["torn"] == 1
    assert m["shards"]["0"]["heat_ms"] == 25.0        # the corrupt max lost
    assert inherited_heat(root, 0) == 25.0


def test_heat_ledger_survives_sigkill(tmp_path):
    root = str(tmp_path)
    child = (
        "import sys\n"
        "from hyperopt_tpu.obs.load import HeatLedger, heat_path_for\n"
        "led = HeatLedger(heat_path_for(sys.argv[1], 'victim'))\n"
        "i = 0\n"
        "while True:\n"
        "    i += 1\n"
        "    led.append({'kind': 'heat', 'replica': 'victim',\n"
        "                'shard': 0, 'heat_ms': float(i),\n"
        "                'busy_frac': 0.5, 'ts': float(i)})\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(filter(None, (
                   os.path.dirname(os.path.dirname(
                       os.path.abspath(__file__))),
                   os.environ.get("PYTHONPATH")))))
    proc = subprocess.Popen([sys.executable, "-c", child, root], env=env)
    try:
        path = heat_path_for(root, "victim")
        deadline = time.time() + 60.0
        while time.time() < deadline:
            try:
                if open(path, "rb").read().count(b"\n") >= 5:
                    break
            except OSError:
                pass
            time.sleep(0.02)
        else:
            pytest.fail("child never wrote 5 heat records")
        proc.send_signal(signal.SIGKILL)              # mid-write, maybe
    finally:
        proc.kill()
        proc.wait()
    m = read_heat(root)
    # every COMPLETE line survives; the only tolerable artifact of the
    # kill is one torn tail — never a corrupt record, never an exception
    assert m["corrupt"] == 0 and m["torn"] <= 1
    assert m["shards"]["0"]["heat_ms"] >= 5.0
    assert inherited_heat(root, 0) == m["shards"]["0"]["heat_ms"]


def _replica(root, rid, n_shards=2, **kw):
    return FleetReplica(root, n_shards=n_shards, replica_id=rid,
                        addr=f"http://{rid}", lease_ttl=5.0,
                        scheduler_kwargs={"wave_window": 0.0}, **kw)


def _age_lease(replica, shard, sec=60.0):
    path = replica.leases._lease_path(f"shard{shard:04d}")
    t = time.time() - sec
    os.utime(path, (t, t))


def test_adoption_inherits_heat_and_healthz_carries_cost(tmp_path):
    root = str(tmp_path / "store")
    a = _replica(root, "rep-a")
    a.join()
    assert a.adopt(0)
    sched = a.schedulers[0]
    assert sched.load is not None                     # armed by default
    assert sched.load.shard == 0 and sched.load.replica == "rep-a"
    sched.load.observe_tick([("s", 2)], device_sec=0.05)
    a._roll_heat(force=True)
    hz = a.healthz()
    assert hz["shards"]["0"]["heat_ms"] == pytest.approx(50.0)
    assert "busy_frac" in hz["shards"]["0"]
    assert hz["load"]["heat_ms"] == pytest.approx(50.0)
    assert hz["replica_addrs"]["rep-a"] == "http://rep-a"

    # the crash: lease goes stale, no drain, no handoff record
    _age_lease(a, 0)
    os.utime(a._replica_path(), (time.time() - 600,) * 2)

    b = _replica(root, "rep-b")
    b.join()
    b.manage_once()                                   # reclaims + adopts
    assert 0 in b.schedulers
    # adoption inherits the shard's accumulated heat from the ledger —
    # the shard did not cool off by moving
    assert b.schedulers[0].load.inherited_ms == pytest.approx(50.0)
    assert b.schedulers[0].load.heat_ms == pytest.approx(50.0)
    b.leave()


def test_graceful_handoff_flushes_heat_before_release(tmp_path):
    root = str(tmp_path / "store")
    a = _replica(root, "rep-a")
    a.join()
    assert a.adopt(1)
    a.schedulers[1].load.observe_tick([("s", 1)], device_sec=0.03)
    assert a.handoff(1)
    m = read_heat(root)
    assert m["shards"]["1"]["heat_ms"] == pytest.approx(30.0)
    assert inherited_heat(root, 1) == pytest.approx(30.0)
    a.leave()


def test_volunteer_handoff_releases_hottest_shard_first(tmp_path):
    root = str(tmp_path / "store")
    a = _replica(root, "rep-a")
    a.join()
    assert a.adopt(0) and a.adopt(1)
    # shard 0 is the hot one — under the OLD count-only pick the
    # volunteer would release the highest shard number (1)
    a.schedulers[0].load.observe_tick([("s", 1)], device_sec=0.9)
    a.schedulers[1].load.observe_tick([("s", 1)], device_sec=0.001)
    b = _replica(root, "rep-b")
    b.join()
    a.manage_once()                   # 2 held > target 1 → volunteer one
    assert 0 not in a.schedulers      # the HOTTEST went first
    assert 1 in a.schedulers
    # and the released heat is durable for the adopter to inherit
    assert inherited_heat(root, 0) == pytest.approx(900.0)
    a.leave()
    b.leave()


# ---------------------------------------------------------------------------
# the skew SLO objective + env knobs
# ---------------------------------------------------------------------------


def test_env_knobs(monkeypatch):
    monkeypatch.delenv("HYPEROPT_TPU_LOAD", raising=False)
    assert parse_load()                         # default ON for serving
    for off in ("0", "off", "false", "no"):
        assert not parse_load({"HYPEROPT_TPU_LOAD": off})
    assert parse_load({"HYPEROPT_TPU_LOAD": "1"})
    # the SLO rider: default on, explicit off, and the token grammar
    assert parse_load_slo({}) == LOAD_TARGETS
    assert parse_load_slo({}) is not LOAD_TARGETS     # a copy, not the map
    assert parse_load_slo({"HYPEROPT_TPU_LOAD_SLO": "off"}) is None
    t = parse_load_slo({"HYPEROPT_TPU_LOAD_SLO": "skew=5"})
    assert t["imbalance"]["skew_max"] == 5.0
    t = parse_load_slo({"HYPEROPT_TPU_LOAD_SLO": "balanced=5"})
    assert t["imbalance"]["target"] == pytest.approx(0.95)
    # malformed tokens warn once and fall back to the defaults
    assert parse_load_slo(
        {"HYPEROPT_TPU_LOAD_SLO": "skew=banana"}) == LOAD_TARGETS
    assert parse_load_slo(
        {"HYPEROPT_TPU_LOAD_SLO": "skew=0.5"}) == LOAD_TARGETS


def test_slo_imbalance_objective_records():
    slo = SLOPlane(metrics=None, clock=lambda: 1000.0)
    slo.add_objective("imbalance", LOAD_TARGETS["imbalance"])
    assert slo.objectives["imbalance"].target == 0.90
    for _ in range(9):
        slo.record_load(False, now=1000.0)            # skew breaches burn
    slo.record_load(True, now=1000.0)
    st = slo.status(now=1000.0)["imbalance"]
    assert st["budget_remaining_frac"] < 1.0
    # disarmed plane: record_load is a no-op, not a KeyError
    SLOPlane(metrics=None).record_load(True)


def test_server_feeds_skew_slo_from_merged_view():
    sched = StudyScheduler(wal=False, quality=False, load=CostLedger())
    srv = ServiceHTTPServer(0, scheduler=sched, trace=False)
    assert srv.load_skew_max == LOAD_TARGETS["imbalance"]["skew_max"]
    assert "imbalance" in srv.slo.objectives
    # a single unbound plane has no shards table → skew 1.0 → balanced
    sched.load.observe_tick([("a", 1)], device_sec=0.001)
    merged = srv._refresh_load_gauges()
    assert merged["heat_skew"] == 1.0


# ---------------------------------------------------------------------------
# the new bench keys really gate
# ---------------------------------------------------------------------------


def _bench_rec(ts, **keys):
    return {"kind": "bench", "ts": ts, "backend": "cpu",
            "source": "test", "keys": keys}


def test_attribution_overhead_gates_absolute_from_first_run():
    """``attribution_overhead_frac`` uses the fixed absolute bar (the
    quality/checksum overhead pattern): it gates with NO history at
    all — the very first recorded round already enforces ≤5%."""
    import bench_gate
    from hyperopt_tpu.obs.trajectory import KEY_DIRECTIONS

    old = _bench_rec(0.0, trials_per_sec=100.0)   # no load keys at all
    over = _bench_rec(1.0, attribution_overhead_frac=0.09)
    regs, _ = bench_gate.windowed_compare([old], over, KEY_DIRECTIONS)
    assert any("attribution_overhead_frac" in r for r in regs)
    ok = _bench_rec(1.0, attribution_overhead_frac=0.04)
    regs, _ = bench_gate.windowed_compare([old], ok, KEY_DIRECTIONS)
    assert regs == []


def test_shard_heat_skew_gates_windowed_lower_is_better():
    import bench_gate
    from hyperopt_tpu.obs.trajectory import KEY_DIRECTIONS

    history = [_bench_rec(float(i), shard_heat_skew=2.0) for i in range(3)]
    bad = _bench_rec(3.0, shard_heat_skew=3.0)        # +50% > the 30% bar
    regs, _ = bench_gate.windowed_compare(history, bad, KEY_DIRECTIONS)
    assert any("shard_heat_skew" in r for r in regs)
    ok = _bench_rec(3.0, shard_heat_skew=2.2)
    regs, _ = bench_gate.windowed_compare(history, ok, KEY_DIRECTIONS)
    assert regs == []


# ---------------------------------------------------------------------------
# render surfaces: report --fleet, Perfetto heat tracks
# ---------------------------------------------------------------------------


def test_report_fleet_view(tmp_path, capsys):
    from hyperopt_tpu.obs.report import main, render_fleet_load

    root = str(tmp_path)
    led = HeatLedger(heat_path_for(root, "rep-a"))
    for i, (shard, h) in enumerate([(0, 100.0), (0, 9000.0), (1, 10.0),
                                    (2, 10.0), (3, 10.0)]):
        led.append({"kind": "heat", "replica": "rep-a", "shard": shard,
                    "heat_ms": h, "busy_frac": 0.7, "ts": float(i)})
    text = render_fleet_load(root)
    assert "fleet load" in text and "shard0" in text
    assert "SKEW" in text       # 9000 vs 3×10: skew ≈ 4.0 > the 3.0x bound
    assert "rep-a" in text
    assert main(["--fleet", root]) == 0
    assert "heat skew" in capsys.readouterr().out
    # --fleet is its own view and text-only
    assert main(["--fleet", root, "--trend"]) == 2
    assert main(["--fleet", root, "--format", "json"]) == 2
    assert main(["--fleet", str(tmp_path / "nope")]) == 2


def test_export_emits_per_shard_heat_counters(tmp_path):
    from hyperopt_tpu.obs.export import write_trace

    stream = [
        {"kind": "run_meta", "ts": 1.0, "run_id": "r"},
        {"kind": "metrics", "ts": 2.0, "snapshot": {
            "metrics": {"service.load.shard.3.heat_ms": 1234.0},
            "load": {"shards": {"5": {"heat_ms": 77.0}}}}},
    ]
    out = str(tmp_path / "trace.json")
    write_trace(out, [("s", iter(stream))])
    events = json.load(open(out))["traceEvents"]
    heat = {e["name"]: e for e in events if e.get("cat") == "load"}
    assert heat["heat.shard3"]["args"]["heat_ms"] == 1234.0
    assert heat["heat.shard5"]["args"]["heat_ms"] == 77.0
    assert all(e["ph"] == "C" for e in heat.values())
