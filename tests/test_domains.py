"""Domain-zoo tests (parity target: hyperopt/tests/test_domains.py sym:
CasePerDomain) — every zoo domain runs under random search; the optimizing
suggesters hit their loss targets on representative domains."""

import numpy as np
import pytest

from hyperopt_tpu import Trials, fmin
from hyperopt_tpu.algos import rand, tpe
from hyperopt_tpu.zoo import ZOO, branin, hartmann6


@pytest.mark.parametrize("name", sorted(ZOO))
def test_domain_runs_under_rand(name):
    domain = ZOO[name]
    t = Trials()
    fmin(domain.objective, domain.space, algo=rand.suggest, max_evals=20,
         trials=t, rstate=np.random.default_rng(0), show_progressbar=False)
    losses = [l for l in t.losses() if l is not None]
    assert len(losses) == 20
    assert np.all(np.isfinite(losses))


@pytest.mark.slow
@pytest.mark.parametrize("name", sorted(ZOO))
def test_tpe_beats_random_zoo_wide(name):
    # round-5 verdict #7: the reference's suggester doctrine is
    # TPE-beats-random across the WHOLE zoo (hyperopt/tests/test_tpe.py
    # CasePerDomain), not on a favored subset.  Paired seeds, matched eval
    # budget; tolerance admits ties on domains both solve (n_arms) and seed
    # noise on the rest.
    domain = ZOO[name]
    # the ML CV domains cost ~1s/eval in the eager host loop; a smaller
    # paired budget keeps the suite's wall clock sane without changing the
    # comparison's validity
    heavy = name.startswith("ml_")
    seeds, budget = (range(2), 30) if heavy else (range(3), 50)

    # traceable objectives run eagerly in the host loop — jit once so the
    # evals don't pay per-op dispatch.  Branch-shaped host samples (e.g.
    # ml_model_select_cv carries only the live branch's params) cannot
    # trace; fall back to the eager objective on the first failure.
    import jax

    state = {"fn": jax.jit(domain.objective) if domain.traceable
             else domain.objective,
             "jitted": domain.traceable}

    def obj(d):
        # diverged ML fits return NaN; the host loop's reference semantics
        # raise InvalidLoss on NaN, so report those as failed trials (the
        # status='fail' contract) instead
        try:
            v = float(state["fn"](d))
        except Exception:
            if not state["jitted"]:
                raise
            state["fn"], state["jitted"] = domain.objective, False
            v = float(state["fn"](d))
        return {"loss": v, "status": "ok"} if np.isfinite(v) else {
            "status": "fail"}

    def mean_best(algo):
        outs = []
        for s in seeds:
            t = Trials()
            fmin(obj, domain.space, algo=algo, max_evals=budget,
                 trials=t, rstate=np.random.default_rng(s),
                 show_progressbar=False)
            outs.append(min(l for l in t.losses() if l is not None))
        return float(np.mean(outs))

    tpe_mean = mean_best(tpe.suggest)
    rand_mean = mean_best(rand.suggest)
    assert tpe_mean <= rand_mean + 0.05 * abs(rand_mean) + 1e-3, (
        name, tpe_mean, rand_mean)


@pytest.mark.parametrize("name", ["quadratic1", "branin", "q1_choice"])
def test_tpe_hits_loss_target(name):
    domain = ZOO[name]
    best = np.inf
    for seed in range(3):
        t = Trials()
        fmin(domain.objective, domain.space, algo=tpe.suggest, max_evals=100,
             trials=t, rstate=np.random.default_rng(seed), show_progressbar=False)
        best = min(best, min(l for l in t.losses() if l is not None))
        if best < domain.loss_target:
            break
    assert best < domain.loss_target


@pytest.mark.parametrize(
    "name", [n for n, d in sorted(ZOO.items()) if d.traceable]
)
def test_traceable_domains_actually_trace(name):
    # `traceable=True` must literally mean the objective jits and vmaps over
    # flat label dicts (the batched-eval / on-device fmin contract)
    import jax
    import jax.numpy as jnp

    from hyperopt_tpu.spaces import compile_space

    domain = ZOO[name]
    cs = compile_space(domain.space)
    keys = jax.random.split(jax.random.PRNGKey(0), 4)
    flats = jax.vmap(cs.sample_flat)(keys)
    out = jax.jit(jax.vmap(lambda f: domain.objective(cs.assemble(f, traced=True))))(flats)
    assert out.shape == (4,)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_branin_value():
    # known optima of Branin-Hoo
    assert float(branin(-np.pi, 12.275)) == pytest.approx(0.397887, abs=1e-4)
    assert float(branin(np.pi, 2.275)) == pytest.approx(0.397887, abs=1e-4)
    assert float(branin(9.42478, 2.475)) == pytest.approx(0.397887, abs=1e-4)


def test_hartmann6_value():
    xstar = [0.20169, 0.150011, 0.476874, 0.275332, 0.311652, 0.6573]
    assert float(hartmann6(xstar)) == pytest.approx(-3.32237, abs=1e-3)
