"""Child process for tests/test_multihost.py: one controller of an N-process
JAX runtime over virtual CPU devices.

Usage: python _multihost_child.py <coordinator_port> <process_id> [N]

The parent launches N of these (default 2); each joins the distributed
runtime, forms the 8-device global mesh (N × 8/N local), runs the
mesh-sharded batched TPE proposal, gathers the result, and compares it
against the plain single-device computation of the SAME history and keys.
Prints ``MULTIHOST_OK`` on success.
"""

import sys

import numpy as np


def main():
    port, pid = sys.argv[1], int(sys.argv[2])
    n_proc = int(sys.argv[3]) if len(sys.argv) > 3 else 2

    from hyperopt_tpu.parallel import multihost

    multihost.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=n_proc,
        process_id=pid,
    )

    import jax
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    assert jax.process_count() == n_proc, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == 8 // n_proc, jax.local_device_count()

    from hyperopt_tpu import hp
    from hyperopt_tpu.algos import tpe
    from hyperopt_tpu.parallel import sharding
    from hyperopt_tpu.spaces import compile_space

    space = {
        "lr": hp.loguniform("lr", -6, 0),
        "width": hp.quniform("width", 16, 256, 16),
        "act": hp.choice("act", ["relu", "gelu", "tanh"]),
    }
    cs = compile_space(space)
    cfg = {"prior_weight": 1.0, "n_EI_candidates": 64, "gamma": 0.25, "LF": 25}

    # identical history on both controllers (deterministic construction)
    rng = np.random.default_rng(7)
    cap, n_obs = 64, 40
    has = np.zeros(cap, bool)
    has[:n_obs] = True
    history = {
        "losses": np.where(has, rng.normal(size=cap), np.inf).astype(np.float32),
        "has_loss": has,
        "vals": {
            "lr": np.where(has, np.exp(rng.uniform(-6, 0, cap)), 0).astype(np.float32),
            "width": np.where(has, rng.integers(1, 16, cap) * 16.0, 0).astype(np.float32),
            "act": np.where(has, rng.integers(0, 3, cap), 0).astype(np.float32),
        },
        "active": {l: has.copy() for l in cs.labels},
    }

    batch = 16
    mesh = multihost.global_mesh()
    assert int(np.prod(list(mesh.shape.values()))) == 8
    keys = multihost.global_key_batch(0, batch, mesh)
    hist_dev = multihost.replicate_global(history, mesh)

    fn = sharding.suggest_batch_sharded(cs, cfg, mesh)
    out = fn(hist_dev, keys)
    gathered = {
        l: np.asarray(multihost_utils.process_allgather(out[l], tiled=True))
        for l in cs.labels
    }

    # single-device reference on this controller: same math, local arrays
    host_keys = jax.vmap(
        lambda i: jax.random.fold_in(jax.random.PRNGKey(0), i)
    )(jnp.arange(batch, dtype=jnp.uint32))
    plain_fn = jax.jit(jax.vmap(tpe.build_propose(cs, cfg), in_axes=(None, 0)))
    plain = plain_fn(
        jax.tree.map(jnp.asarray, history), host_keys
    )
    for label in cs.labels:
        np.testing.assert_allclose(
            gathered[label], np.asarray(plain[label]), rtol=1e-6, atol=1e-6,
            err_msg=f"multi-process != single-process for {label}",
        )

    # and the candidate-axis collective path executes across processes
    mesh2 = multihost.global_mesh(n_cand_shards=2)
    cand_fn = sharding.propose_sharded_candidates(cs, cfg, mesh2)
    hist2 = multihost.replicate_global(history, mesh2)
    out2 = cand_fn(hist2, jax.random.PRNGKey(3))
    for label in cs.labels:
        v = np.asarray(multihost_utils.process_allgather(out2[label], tiled=True))
        assert np.all(np.isfinite(v)), f"non-finite proposal for {label}"

    # END-TO-END multi-controller fmin (round-5 verdict #2): both
    # controllers run the whole ask->tell loop — global sharded proposals,
    # per-controller evaluation shards, allgather fold, checksum — and the
    # result must match the single-process reference algorithm BITWISE.
    from hyperopt_tpu.parallel.driver import fmin_multihost
    from hyperopt_tpu.zoo import ZOO

    dom = ZOO["branin"]
    obj = lambda d: float(dom.objective(d))  # noqa: E731
    res = fmin_multihost(obj, dom.space, max_evals=48, batch=8, seed=0)
    assert res.n_evals == 48
    ref = fmin_multihost(obj, dom.space, max_evals=48, batch=8, seed=0,
                         _force_single=True)
    assert res.checksum == ref.checksum, (res.checksum, ref.checksum)
    assert res.best_loss == ref.best_loss, (res.best_loss, ref.best_loss)
    np.testing.assert_array_equal(res.losses, ref.losses)
    assert res.best_loss < 2.0, res.best_loss  # it optimized, not just ran

    # checkpointed kill-and-resume ACROSS CONTROLLERS: controller 0 writes
    # per-generation snapshots to a path all processes share; a second run
    # resumes (every controller loads the same state — the resume-agreement
    # allgather verifies it) and must reproduce the uninterrupted 48-eval
    # run bitwise
    import os

    ck = f"/tmp/mh_child_ck_{port}.pkl"
    if pid == 0 and os.path.exists(ck):
        os.remove(ck)
    multihost_utils.sync_global_devices("ck-clean")
    fmin_multihost(obj, dom.space, max_evals=24, batch=8, seed=0,
                   checkpoint_file=ck)
    multihost_utils.sync_global_devices("ck-leg1")
    resumed = fmin_multihost(obj, dom.space, max_evals=48, batch=8, seed=0,
                             checkpoint_file=ck)
    assert resumed.checksum == res.checksum, "resume diverged from straight run"
    np.testing.assert_array_equal(resumed.losses, res.losses)
    multihost_utils.sync_global_devices("ck-done")
    if pid == 0:
        os.remove(ck)

    print(f"MULTIHOST_OK process={pid} fmin_best={res.best_loss:.4f}", flush=True)


if __name__ == "__main__":
    main()
