"""Child process for tests/test_fleet.py and scripts/chaos_smoke.py: one
controller of an elastic fleet.

Usage::

    python _fleet_child.py <fleet_dir> [--seed S] [--max-evals N]
        [--batch B] [--n-shards K] [--lease-ttl T] [--echo-evals]
        [--owner NAME]

Joins the lease plane rooted at ``fleet_dir``, runs the elastic
``fmin_multihost(fleet_dir=...)`` driver on the branin domain, and prints
``FLEET_OK checksum=<hex> evals=<n>`` on success.  ``--echo-evals`` prints
one ``EVAL <k>`` line per objective call (flushed) so a parent can time a
SIGKILL to land mid-generation.  Chaos arms itself from
``HYPEROPT_TPU_CHAOS`` in the child's environment — the parent scripts
hand each controller its own schedule.
"""

import argparse
import sys


def main():
    p = argparse.ArgumentParser()
    p.add_argument("fleet_dir")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max-evals", type=int, default=48)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--n-shards", type=int, default=4)
    p.add_argument("--lease-ttl", type=float, default=2.0)
    p.add_argument("--echo-evals", action="store_true")
    args = p.parse_args()

    from hyperopt_tpu.parallel.driver import fmin_multihost
    from hyperopt_tpu.zoo import ZOO

    dom = ZOO["branin"]
    calls = {"n": 0}

    def obj(d):
        calls["n"] += 1
        if args.echo_evals:
            print(f"EVAL {calls['n']}", flush=True)
        return float(dom.objective(d))

    res = fmin_multihost(
        obj, dom.space, max_evals=args.max_evals, batch=args.batch,
        seed=args.seed, fleet_dir=args.fleet_dir, n_shards=args.n_shards,
        lease_ttl=args.lease_ttl)
    print(f"FLEET_OK checksum={res.checksum} evals={res.n_evals} "
          f"best={res.best_loss:.6f}", flush=True)


if __name__ == "__main__":
    sys.exit(main())
