"""ISSUE 19: the quantized-history fused-suggest megakernel.

CPU lane: the arming ladder (env parsing, space support, backend gate,
lowering-failure disarm) runs for real; the kernel BODY runs through the
Pallas interpreter (``HYPEROPT_TPU_MEGAKERNEL=interpret``) — the same
traced program a TPU would lower, executed as XLA ops.  Agreement with
the jnp cohort is asserted to tolerance, not bitwise: on CPU the
interpreter reproduces the jnp stream exactly (same RNG, same math), but
real Mosaic scheduling may reassociate the streamed accumulations, and
the contract ISSUE 19 gates on is the quality/health trajectory, not
bit-equality (see bench.py ``search_quality``)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hyperopt_tpu import hp, megakernel, pallas_ei, quant
from hyperopt_tpu._env import parse_megakernel
from hyperopt_tpu.algos import tpe
from hyperopt_tpu.base import Domain

SPACE = {
    "x": hp.uniform("x", -5, 5),
    "lr": hp.loguniform("lr", -4, 0),
}

CFG = {"prior_weight": 1.0, "n_EI_candidates": 24, "gamma": 0.25,
       "LF": 25, "ei_select": "argmax", "ei_tau": 1.0, "prior_eps": 0.0}


def _hist_stack(cs, S, cap, rng):
    devs = []
    for s in range(S):
        vals = {l: np.zeros(cap, np.float32) for l in cs.labels}
        act = {l: np.zeros(cap, bool) for l in cs.labels}
        losses = np.full(cap, np.inf, np.float32)
        has = np.zeros(cap, bool)
        for i in range(5 + s):
            # (0.05, 0.9) sits inside the support of every label used in
            # this file (uniform(-5,5), loguniform(-4,0), uniform(0,1))
            for l in cs.labels:
                vals[l][i] = rng.uniform(0.05, 0.9)
                act[l][i] = True
            losses[i] = rng.uniform()
            has[i] = True
        devs.append({"vals": {l: jnp.asarray(vals[l]) for l in cs.labels},
                     "active": {l: jnp.asarray(act[l]) for l in cs.labels},
                     "losses": jnp.asarray(losses),
                     "has_loss": jnp.asarray(has)})
    return jax.tree.map(lambda *xs: jnp.stack(xs), *devs)


def _drive(cs, fn, S, cap, B, seed0=500):
    L = len(cs.labels)
    rows = np.zeros((S, 16, 2 * L + 3), np.float32)
    rows[:, :, -1] = cap
    seeds = np.stack([tpe._seed_words(seed0 + s) for s in range(S)])
    ids = np.asarray([[3 + s, 9 + s] for s in range(S)][:S], np.uint32)
    stack = _hist_stack(cs, S, cap, np.random.default_rng(7))
    _, packed = fn(stack, rows, seeds, ids)
    return np.asarray(packed)


# ---------------------------------------------------------------------------
# the arming ladder
# ---------------------------------------------------------------------------


def test_env_parsing(monkeypatch):
    monkeypatch.delenv("HYPEROPT_TPU_MEGAKERNEL", raising=False)
    assert parse_megakernel() == "off"
    for raw, want in (("0", "off"), ("off", "off"), ("1", "on"),
                      ("on", "on"), ("interpret", "interpret"),
                      ("bogus", "off")):
        monkeypatch.setenv("HYPEROPT_TPU_MEGAKERNEL", raw)
        assert parse_megakernel() == want, raw


def test_pallas_alias_maps_to_on(monkeypatch):
    monkeypatch.delenv("HYPEROPT_TPU_MEGAKERNEL", raising=False)
    monkeypatch.setenv("HYPEROPT_TPU_PALLAS", "1")
    assert megakernel.mode() == "on"
    # explicit megakernel setting wins over the alias
    monkeypatch.setenv("HYPEROPT_TPU_MEGAKERNEL", "interpret")
    assert megakernel.mode() == "interpret"


def test_supports_numeric_only():
    assert megakernel.supports(Domain(None, SPACE).cs)
    for bad in ({"k": hp.randint("k", 4)},
                {"c": hp.choice("c", [1, 2])},
                {"q": hp.quniform("q", 0, 10, 2)}):
        assert not megakernel.supports(Domain(None, bad).cs)


def test_armed_needs_tpu_or_interpret(monkeypatch):
    cs = Domain(None, SPACE).cs
    monkeypatch.setenv("HYPEROPT_TPU_MEGAKERNEL", "1")
    # CPU CI: mode "on" must NOT arm (the jnp program serves) ...
    assert megakernel.armed(cs) == megakernel.pallas_available()
    # ... while interpret arms anywhere
    monkeypatch.setenv("HYPEROPT_TPU_MEGAKERNEL", "interpret")
    assert megakernel.armed(cs)
    monkeypatch.setenv("HYPEROPT_TPU_MEGAKERNEL", "0")
    assert not megakernel.armed(cs)


def test_disarmed_build_is_the_same_program(monkeypatch):
    """MEGAKERNEL=0 and unset hit the SAME cohort-LRU entry — the
    disarmed path is byte-identical by construction, not by luck."""
    cs = Domain(None, SPACE).cs
    monkeypatch.delenv("HYPEROPT_TPU_MEGAKERNEL", raising=False)
    fn_unset = tpe.build_suggest_batched(cs, CFG, 2, 16, 2, donate=False)
    monkeypatch.setenv("HYPEROPT_TPU_MEGAKERNEL", "0")
    fn_off = tpe.build_suggest_batched(cs, CFG, 2, 16, 2, donate=False)
    assert fn_unset is fn_off


# ---------------------------------------------------------------------------
# the fused program (interpret lane)
# ---------------------------------------------------------------------------


def test_interpret_cohort_matches_jnp(monkeypatch):
    cs = Domain(None, SPACE).cs
    S, cap, B = 2, 16, 2
    monkeypatch.delenv("HYPEROPT_TPU_MEGAKERNEL", raising=False)
    want = _drive(cs, tpe.build_suggest_batched(cs, CFG, S, cap, B,
                                                donate=False), S, cap, B)
    monkeypatch.setenv("HYPEROPT_TPU_MEGAKERNEL", "interpret")
    assert megakernel.armed(cs)
    got = _drive(cs, tpe.build_suggest_batched(cs, CFG, S, cap, B,
                                               donate=False), S, cap, B)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_interpret_cohort_key_forks(monkeypatch):
    """Armed and disarmed builds may not share a cohort-LRU slot — the
    compile plane's bank must treat them as different programs."""
    cs = Domain(None, SPACE).cs
    monkeypatch.delenv("HYPEROPT_TPU_MEGAKERNEL", raising=False)
    k_off = tpe.cohort_key(cs, CFG, 2, 16, 2, donate=False)
    monkeypatch.setenv("HYPEROPT_TPU_MEGAKERNEL", "interpret")
    k_on = tpe.cohort_key(cs, CFG, 2, 16, 2, donate=False)
    assert k_off != k_on


def test_quantized_cohort_serves_in_bounds(monkeypatch):
    """int8-coded history through the ARMED fused program: proposals are
    finite and inside the space's support (the dequant boundary feeds
    the kernel f32 tables)."""
    cs = Domain(None, SPACE).cs
    S, cap, B = 2, 16, 2
    monkeypatch.setenv("HYPEROPT_TPU_MEGAKERNEL", "interpret")
    name, qp = quant.resolve(cs, "int8", context="test")
    assert name == "int8" and qp is not None
    fn = tpe.build_suggest_batched(cs, CFG, S, cap, B, donate=False,
                                   hist_dtype="int8")
    stack = _hist_stack(cs, S, cap, np.random.default_rng(7))
    enc = {l: quant.quantize_np(np.asarray(stack["vals"][l]), qp[l],
                                "int8") for l in cs.labels}
    stack = dict(stack, vals={l: jnp.asarray(enc[l]) for l in cs.labels},
                 losses=jnp.asarray(np.asarray(stack["losses"]),
                                    jnp.bfloat16))
    L = len(cs.labels)
    rows = np.zeros((S, 16, 2 * L + 3), np.float32)
    rows[:, :, -1] = cap
    seeds = np.stack([tpe._seed_words(600 + s) for s in range(S)])
    ids = np.asarray([[3, 9], [4, 10]], np.uint32)
    _, packed = fn(stack, rows, seeds, ids)
    packed = np.asarray(packed, np.float64)
    assert np.isfinite(packed).all()
    xi = cs.labels.index("x")
    li = cs.labels.index("lr")
    assert (packed[:, :, xi] >= -5).all() and (packed[:, :, xi] <= 5).all()
    assert (packed[:, :, li] > 0).all() and (packed[:, :, li] <= 1.0).all()


def test_lowering_failure_falls_back_and_counts(monkeypatch):
    """A kernel that fails to lower disarms the space (warn-once +
    counter), and build_suggest_batched serves the jnp program under the
    recomputed plain key — an ask never fails."""
    space = {"z": hp.uniform("z", 0, 1)}
    cs = Domain(None, space).cs
    monkeypatch.setenv("HYPEROPT_TPU_MEGAKERNEL", "interpret")

    def boom(*a, **k):
        raise RuntimeError("synthetic Mosaic lowering failure")

    monkeypatch.setattr(megakernel, "_build_fused", boom)
    before = megakernel.fallback_count()
    try:
        fn = tpe.build_suggest_batched(cs, CFG, 2, 16, 2, donate=False)
        assert fn is not None
        assert megakernel.fallback_count() == before + 1
        assert cs.signature() in megakernel._failed
        assert not megakernel.armed(cs)  # stays disarmed for this space
        # the fallback program really serves
        out = _drive(cs, fn, 2, 16, 2)
        assert np.isfinite(np.asarray(out, np.float64)).all()
        # and a repeat build is a cache hit, not another fallback event
        tpe.build_suggest_batched(cs, CFG, 2, 16, 2, donate=False)
        assert megakernel.fallback_count() == before + 1
    finally:
        megakernel._failed.discard(cs.signature())
        megakernel._warned.discard(cs.signature())


# ---------------------------------------------------------------------------
# the absorbed EI-pair kernel + shim
# ---------------------------------------------------------------------------


def test_pallas_ei_is_a_shim():
    assert pallas_ei.ei_diff is megakernel.ei_diff
    assert pallas_ei.ei_diff_reference is megakernel.ei_diff_reference
    assert pallas_ei.pallas_available is megakernel.pallas_available


def test_ei_diff_interpret_matches_reference(monkeypatch):
    monkeypatch.setenv("HYPEROPT_TPU_MEGAKERNEL", "interpret")
    rng = np.random.default_rng(3)
    m = 17
    def mix():
        w = np.abs(rng.random(m)).astype(np.float32)
        w /= w.sum()
        return (jnp.asarray(w),
                jnp.asarray(rng.uniform(-3, 3, m).astype(np.float32)),
                jnp.asarray(rng.uniform(0.1, 2.0, m).astype(np.float32)))
    wb, mb, sb = mix()
    wa, ma, sa = mix()
    x = jnp.asarray(rng.uniform(-4, 4, 1024).astype(np.float32))
    got = megakernel.ei_diff(x, wb, mb, sb, wa, ma, sa)  # interpret lane
    want = megakernel.ei_diff_reference(x, wb, mb, sb, wa, ma, sa)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
