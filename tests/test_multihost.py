"""Multi-process distribution tests.

Doctrine (SURVEY.md §4): "distributed" is tested as REAL local processes —
the reference spins up a real mongod + real worker subprocesses for
test_mongoexp; here two actual jax controllers form one global runtime via
``jax.distributed.initialize`` (the DCN-analog boundary) over virtual CPU
devices and must produce the same proposals as a single process.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_multihost_child.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_mesh_matches_single_process():
    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never claim the real chip
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, CHILD, str(port), str(pid)],
            env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    for rc, out, err in outs:
        assert rc == 0, f"child failed rc={rc}\nstdout:\n{out}\nstderr:\n{err[-3000:]}"
        assert "MULTIHOST_OK" in out, out
