"""Multi-process distribution tests.

Doctrine (SURVEY.md §4): "distributed" is tested as REAL local processes —
the reference spins up a real mongod + real worker subprocesses for
test_mongoexp; here two actual jax controllers form one global runtime via
``jax.distributed.initialize`` (the DCN-analog boundary) over virtual CPU
devices and must produce the same proposals as a single process.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_multihost_child.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_mesh_matches_single_process():
    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never claim the real chip
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, CHILD, str(port), str(pid)],
            env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    for rc, out, err in outs:
        assert rc == 0, f"child failed rc={rc}\nstdout:\n{out}\nstderr:\n{err[-3000:]}"
        assert "MULTIHOST_OK" in out, out


@pytest.mark.slow
def test_four_process_mesh_matches_single_process():
    # N>2 generality: 4 controllers × 2 virtual devices form the same
    # 8-device global mesh; the round-robin evaluation shards, the
    # allgather fold, and the divergence checksum must all hold at P=4
    # exactly as at P=2 (the child asserts bitwise identity with the
    # single-process reference)
    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never claim the real chip
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, CHILD, str(port), str(pid), "4"],
            env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for pid in range(4)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=420)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    for rc, out, err in outs:
        assert rc == 0, f"child failed rc={rc}\nstdout:\n{out}\nstderr:\n{err[-3000:]}"
        assert "MULTIHOST_OK" in out, out


def test_fmin_multihost_single_process_deterministic():
    # the same SPMD driver runs single-process (P=1): deterministic in seed,
    # optimizes, and exposes the divergence-guard checksum
    import numpy as np

    from hyperopt_tpu.parallel.driver import fmin_multihost
    from hyperopt_tpu.zoo import ZOO

    dom = ZOO["branin"]
    obj = lambda d: float(dom.objective(d))  # noqa: E731
    r1 = fmin_multihost(obj, dom.space, max_evals=64, batch=16, seed=0)
    r2 = fmin_multihost(obj, dom.space, max_evals=64, batch=16, seed=0)
    assert r1.n_evals == 64 and r1.losses.shape == (64,)
    assert r1.checksum == r2.checksum
    assert r1.best_loss == r2.best_loss < 2.0
    r3 = fmin_multihost(obj, dom.space, max_evals=64, batch=16, seed=1)
    assert r3.checksum != r1.checksum  # seed actually matters


def test_fmin_multihost_conditional_space():
    # conditional space: int coercion for choice indices, activation masks,
    # and failed-trial (exception) handling
    import numpy as np

    from hyperopt_tpu.parallel.driver import fmin_multihost
    from hyperopt_tpu.zoo import ZOO

    dom = ZOO["q1_choice"]

    calls = {"n": 0}

    def obj(d):
        calls["n"] += 1
        if calls["n"] % 7 == 3:
            raise RuntimeError("flaky trial")
        return float(dom.objective(d))

    r = fmin_multihost(obj, dom.space, max_evals=48, batch=8, seed=0)
    assert r.n_evals == 48
    assert np.isfinite(r.best_loss) and r.best_loss < 3.0
    assert "x" in r.best  # structured sample assembled from the best flat


def test_fmin_multihost_to_trials_bridge():
    # the MultihostResult -> Trials bridge gives reference-shaped docs:
    # argmin/best_trial/losses/plotting inputs work, inactive conditional
    # params have empty idxs, failed trials carry status=fail
    import numpy as np

    from hyperopt_tpu.parallel.driver import fmin_multihost
    from hyperopt_tpu.zoo import ZOO

    dom = ZOO["q1_choice"]
    calls = {"n": 0}

    def obj(d):
        calls["n"] += 1
        if calls["n"] % 9 == 5:
            raise RuntimeError("flaky")
        return float(dom.objective(d))

    r = fmin_multihost(obj, dom.space, max_evals=32, batch=8, seed=0)
    t = r.to_trials()
    assert len(t) == 32
    losses = t.losses()
    finite = [l for l in losses if l is not None]
    assert min(finite) == r.best_loss
    assert any(l is None for l in losses)  # the flaky trials became fails
    doc = t.best_trial
    assert doc["state"] == 2 and doc["result"]["status"] == "ok"
    # q1_choice is conditional: some docs must have an inactive param with
    # empty idxs/vals
    assert any(
        any(len(v) == 0 for v in d["misc"]["vals"].values())
        for d in t.trials
    )
    # argmin recovers the best flat values recorded in the result
    for l, v in t.argmin.items():
        assert abs(float(r.vals[l][np.argmin(np.where(
            np.isfinite(r.losses), r.losses, np.inf))]) - float(v)) < 1e-6


def test_fmin_multihost_checkpoint_resume_bitwise():
    # kill-and-resume must continue the EXACT trial sequence of an
    # uninterrupted run: generation seeds depend only on (seed, gen), the
    # checkpoint lands on generation boundaries, and the fold digest is
    # replayed from the saved raw losses (incl. NaN for raised trials)
    import os
    import tempfile

    import numpy as np

    from hyperopt_tpu.parallel.driver import fmin_multihost
    from hyperopt_tpu.zoo import ZOO

    dom = ZOO["branin"]
    calls = {"n": 0}

    def obj(d):
        calls["n"] += 1
        if calls["n"] % 11 == 4:
            raise RuntimeError("flaky")  # raw-loss NaN must survive resume
        return float(dom.objective(d))

    with tempfile.TemporaryDirectory() as tmp:
        ck = os.path.join(tmp, "mh.ckpt")

        # uninterrupted reference (no checkpoint involved)
        calls["n"] = 0
        ref = fmin_multihost(obj, dom.space, max_evals=48, batch=8, seed=5)

        # first leg: 24 evals, checkpoint written at each generation
        calls["n"] = 0
        fmin_multihost(obj, dom.space, max_evals=24, batch=8, seed=5,
                       checkpoint_file=ck)
        assert os.path.exists(ck)
        # resumed leg: continue to 48.  The objective's call counter keeps
        # running from the first leg (25th call overall = trial 25), exactly
        # as a restarted process re-evaluating only NEW trials would see.
        res = fmin_multihost(obj, dom.space, max_evals=48, batch=8, seed=5,
                             checkpoint_file=ck)
        assert res.checksum == ref.checksum
        assert res.best_loss == ref.best_loss
        np.testing.assert_array_equal(res.losses, ref.losses)

        # changed run parameters are refused (bitwise resume impossible)
        import pytest as _pytest

        with _pytest.raises(ValueError, match="identical run parameters"):
            fmin_multihost(obj, dom.space, max_evals=64, batch=7, seed=5,
                           checkpoint_file=ck)
        with _pytest.raises(ValueError, match="identical run parameters"):
            fmin_multihost(obj, dom.space, max_evals=64, batch=8, seed=6,
                           checkpoint_file=ck)

        # a run that completed on a partial final generation cannot be
        # extended bitwise — clear refusal, not a misleading batch hint
        ck2 = os.path.join(tmp, "partial.ckpt")
        fmin_multihost(obj, dom.space, max_evals=20, batch=8, seed=5,
                       checkpoint_file=ck2)  # final generation B=4
        with _pytest.raises(ValueError, match="partial final generation"):
            fmin_multihost(obj, dom.space, max_evals=48, batch=8, seed=5,
                           checkpoint_file=ck2)
        # but re-materializing the completed result (same or smaller
        # max_evals) still works, even when cap must grow past max_evals
        r20 = fmin_multihost(obj, dom.space, max_evals=20, batch=8, seed=5,
                             checkpoint_file=ck2)
        assert r20.n_evals == 20
        r8 = fmin_multihost(obj, dom.space, max_evals=8, batch=8, seed=5,
                            checkpoint_file=ck2)
        assert r8.n_evals == 20  # restored history is the run's true length


def test_fmin_multihost_all_failed_raises():
    import pytest as _pytest

    from hyperopt_tpu import hp
    from hyperopt_tpu.exceptions import AllTrialsFailed
    from hyperopt_tpu.parallel.driver import fmin_multihost

    def bad(_):
        raise RuntimeError("boom")

    with _pytest.raises(AllTrialsFailed):
        fmin_multihost(bad, {"x": hp.uniform("x", 0, 1)}, max_evals=8,
                       batch=8, seed=0)
