"""Multi-device sharding tests on the 8-device CPU mesh from conftest.

Mirrors the reference doctrine of testing "distributed" as multi-process on
one host (SURVEY.md §4): here multi-chip is 8 virtual CPU devices.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hyperopt_tpu import hp
from hyperopt_tpu.algos import tpe
from hyperopt_tpu.parallel import sharding
from hyperopt_tpu.spaces import compile_space


pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)


SPACE = {
    "x": hp.uniform("x", -5, 5),
    "lr": hp.loguniform("lr", -4, 0),
    "k": hp.randint("k", 4),
}
CFG = {"prior_weight": 1.0, "n_EI_candidates": 64, "gamma": 0.25, "LF": 25}


def _history(cs, n=30, cap=64, seed=0):
    rng = np.random.default_rng(seed)
    losses = np.full(cap, np.inf, np.float32)
    has = np.zeros(cap, bool)
    losses[:n] = rng.normal(size=n)
    has[:n] = True
    vals = {}
    for label in cs.labels:
        fam = cs.params[label].dist.family
        if fam == "randint":
            v = rng.integers(0, 4, size=cap)
        elif fam == "loguniform":
            v = np.exp(rng.uniform(-4, 0, size=cap))
        else:
            v = rng.uniform(-5, 5, size=cap)
        vals[label] = jnp.asarray(np.where(has, v, 0).astype(np.float32))
    return {
        "losses": jnp.asarray(losses),
        "has_loss": jnp.asarray(has),
        "vals": vals,
        "active": {l: jnp.asarray(has) for l in cs.labels},
    }


def test_make_mesh_shapes():
    mesh = sharding.make_mesh(8, n_cand_shards=2)
    assert dict(mesh.shape) == {"trials": 4, "cand": 2}
    with pytest.raises(ValueError):
        sharding.make_mesh(8, n_cand_shards=3)


def test_suggest_batch_sharded_matches_single_device():
    cs = compile_space(SPACE)
    hist = _history(cs)
    mesh = sharding.make_mesh(8)
    keys = jax.vmap(lambda i: jax.random.fold_in(jax.random.PRNGKey(0), i))(
        jnp.arange(16, dtype=jnp.uint32)
    )
    hist_dev = sharding.replicate_history(hist, mesh)
    out_sharded = sharding.suggest_batch_sharded(cs, CFG, mesh)(hist_dev, keys)
    out_plain = jax.jit(jax.vmap(tpe.build_propose(cs, CFG), in_axes=(None, 0)))(
        hist, keys
    )
    for label in cs.labels:
        np.testing.assert_allclose(
            np.asarray(out_sharded[label]), np.asarray(out_plain[label]),
            rtol=1e-5, atol=1e-5,
        )
    # the batch really is laid out across all 8 devices
    assert len(out_sharded["x"].sharding.device_set) == 8


def test_propose_sharded_candidates_valid_and_deterministic():
    cs = compile_space(SPACE)
    hist = _history(cs)
    mesh = sharding.make_mesh(8, n_cand_shards=2)
    hist_dev = sharding.replicate_history(hist, mesh)
    fn = sharding.propose_sharded_candidates(cs, CFG, mesh)
    out1 = jax.tree.map(np.asarray, fn(hist_dev, jax.random.PRNGKey(1)))
    out2 = jax.tree.map(np.asarray, fn(hist_dev, jax.random.PRNGKey(1)))
    for label in cs.labels:
        np.testing.assert_array_equal(out1[label], out2[label])
    assert -5 <= out1["x"] <= 5
    assert np.exp(-4) - 1e-5 <= out1["lr"] <= 1 + 1e-5
    assert out1["k"] in range(4)


def test_propose_sharded_candidates_pads_indivisible():
    # ISSUE 6 satellite: a candidate count that does not divide the shard
    # count used to raise ValueError; now the local batch pads up to the
    # next multiple and padded candidates' EI masks to -inf (they can
    # never win), so the call just works
    cs = compile_space(SPACE)
    hist = _history(cs)
    mesh = sharding.make_mesh(8, n_cand_shards=2)
    hist_dev = sharding.replicate_history(hist, mesh)
    fn = sharding.propose_sharded_candidates(
        cs, dict(CFG, n_EI_candidates=63), mesh
    )
    out = jax.tree.map(np.asarray, fn(hist_dev, jax.random.PRNGKey(5)))
    assert -5 <= out["x"] <= 5
    assert np.exp(-4) - 1e-5 <= out["lr"] <= 1 + 1e-5
    assert out["k"] in range(4)
    out2 = jax.tree.map(np.asarray, fn(hist_dev, jax.random.PRNGKey(5)))
    for label in cs.labels:
        np.testing.assert_array_equal(out[label], out2[label])


def test_propose_sharded_candidates_batched():
    # the round-6 growth: full sharded BATCHES of proposals (each scored
    # over the distributed candidate pool), not one winner per dispatch
    cs = compile_space(SPACE)
    hist = _history(cs)
    mesh = sharding.make_mesh(8, n_cand_shards=2)
    hist_dev = sharding.replicate_history(hist, mesh)
    fn = sharding.propose_sharded_candidates(
        cs, dict(CFG, ei_select="softmax"), mesh, packed=True, batch=16
    )
    keys = jax.vmap(lambda i: jax.random.fold_in(jax.random.PRNGKey(3), i))(
        jnp.arange(16, dtype=jnp.uint32)
    )
    mat = np.asarray(fn(hist_dev, keys))
    assert mat.shape == (16, len(cs.labels))
    xj = list(cs.labels).index("x")
    assert ((mat[:, xj] >= -5) & (mat[:, xj] <= 5)).all()
    # per-proposal keys: a wide batch must not collapse onto one point
    assert len(np.unique(mat[:, xj])) > 1


@pytest.mark.skip(
    reason="dryrun_multichip spawns a multi-process CPU mesh, which this "
           "jaxlib build cannot host (distributed init fails under "
           "forced-CPU multi-process; pre-existing, noted in CHANGES.md "
           "PR 6).  The single-chip half is covered by every other test "
           "in this file; re-enable when jaxlib grows multi-process CPU "
           "support or CI gets real multi-host hardware.")
def test_graft_entry_single_chip_and_multichip():
    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    assert set(out) == set(compile_space(__graft_entry__._flagship_space()).labels)
    __graft_entry__.dryrun_multichip(8)


def test_suggest_sharded_fmin_end_to_end():
    # round-5 verdict #6: the sharded kernels must be reachable from the
    # user-facing algo= boundary — a real fmin on the 8-device CPU mesh,
    # trial-axis sharding for queue batches
    import numpy as np

    from hyperopt_tpu import Trials, fmin
    from hyperopt_tpu.algos import tpe
    from hyperopt_tpu.zoo import ZOO

    dom = ZOO["branin"]
    t = Trials()
    algo = tpe.suggest_sharded(n_startup_jobs=16, n_EI_candidates=32)
    fmin(dom.objective, dom.space, algo=algo, max_evals=64, trials=t,
         max_queue_len=8, rstate=np.random.default_rng(0),
         show_progressbar=False)
    assert len(t) == 64
    best = min(l for l in t.losses() if l is not None)
    assert best < 2.0, best


def test_propose_sharded_candidates_prior_eps_engages():
    # review regression pin: the candidate-sharded path must honor
    # cfg["prior_eps"] (the exploration floor) — with eps=1.0 EVERY
    # proposal is a fresh prior draw, so a batch cannot collapse onto the
    # pooled EI winner
    cs = compile_space(SPACE)
    hist = _history(cs)
    mesh = sharding.make_mesh(8, n_cand_shards=2)
    hist_dev = sharding.replicate_history(hist, mesh)
    keys = jax.vmap(lambda i: jax.random.fold_in(jax.random.PRNGKey(7), i))(
        jnp.arange(32, dtype=jnp.uint32)
    )
    base = dict(CFG, ei_select="argmax")
    off = sharding.propose_sharded_candidates(
        cs, base, mesh, packed=True, batch=32)(hist_dev, keys)
    on = sharding.propose_sharded_candidates(
        cs, dict(base, prior_eps=1.0), mesh, packed=True, batch=32)(
        hist_dev, keys)
    xj = list(cs.labels).index("x")
    off_x, on_x = np.asarray(off)[:, xj], np.asarray(on)[:, xj]
    assert not np.array_equal(off_x, on_x)
    # eps=1.0 draws spread like the prior instead of stacking on one mode
    assert len(np.unique(on_x)) == 32
    assert ((on_x >= -5) & (on_x <= 5)).all()


def test_suggest_sharded_batched_candidate_axis_fmin():
    # queue batches AND n_cand_shards > 1: the round-6 path — every
    # proposal in the batch scored over the distributed candidate pool
    import numpy as np

    from hyperopt_tpu import Trials, fmin, hp
    from hyperopt_tpu.algos import tpe

    t = Trials()
    algo = tpe.suggest_sharded(n_cand_shards=2, n_startup_jobs=12,
                               n_EI_candidates=48)
    fmin(lambda d: (d["x"] - 2.0) ** 2, {"x": hp.uniform("x", -5, 5)},
         algo=algo, max_evals=36, trials=t, max_queue_len=4,
         rstate=np.random.default_rng(2), show_progressbar=False)
    assert len(t) == 36
    assert min(l for l in t.losses() if l is not None) < 1.0


def test_suggest_sharded_candidate_axis_fmin():
    # single-proposal queue -> candidate-axis shard_map path (all-gather EI
    # argmax across devices), end to end through fmin
    import numpy as np

    from hyperopt_tpu import Trials, fmin, hp
    from hyperopt_tpu.algos import tpe

    t = Trials()
    algo = tpe.suggest_sharded(n_cand_shards=2, n_startup_jobs=10,
                               n_EI_candidates=64)
    fmin(lambda d: (d["x"] - 2.0) ** 2, {"x": hp.uniform("x", -5, 5)},
         algo=algo, max_evals=30, trials=t,
         rstate=np.random.default_rng(1), show_progressbar=False)
    assert len(t) == 30
    assert min(l for l in t.losses() if l is not None) < 1.0
