"""Chaos gate (``CHAOS_GATE=1 ./run_tests.sh``): a 3-controller elastic
fleet survives a seeded SIGTERM/SIGKILL schedule and converges to a final
history BIT-IDENTICAL to the undisturbed same-seed run.

What it drives, end-to-end with real processes (no fakes — the same
doctrine as tests/test_multihost.py):

1. launches three ``tests/_fleet_child.py`` controllers on one shared
   fleet store, each with its own deterministic ``HYPEROPT_TPU_CHAOS``
   schedule: controller 0 takes a SIGTERM at its 3rd generation
   (flight-recorder dump path), controller 1 takes a SIGKILL at its 2nd
   shard publish (stale-lease reclaim path, no dump possible), controller
   2 runs clean and must finish;
2. asserts every surviving controller printed the SAME checksum, equal to
   an in-process undisturbed reference run (fleet mode, one controller,
   fresh store) AND to the collective single-process driver — the full
   bitwise-convergence claim of ISSUE 8;
3. asserts the SIGTERM'd controller's flight dump is readable through
   ``FileStore.read_flight_dumps()`` and records the chaos injection;
4. replays the finished store with one more controller ("resumed at a
   different size") and asserts the replay is bitwise-identical too.

Exit 0 prints ``CHAOS_SMOKE_OK``.

NOTE: this box has ONE CPU core (see the verify skill's hardware facts) —
run the gate sequentially, never concurrently with another
CPU-saturating job (e.g. a full pytest run): three jax controllers
starved of cycles can blow realistic lease/barrier budgets and the gate
then measures the scheduler, not the fleet.
"""

import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHILD = os.path.join(REPO, "tests", "_fleet_child.py")
sys.path.insert(0, REPO)

SEED = 0
MAX_EVALS = 48
BATCH = 8
N_SHARDS = 4
LEASE_TTL = 2.0


def _child_env(chaos_spec):
    from hyperopt_tpu._env import forced_cpu_env

    env = forced_cpu_env(dict(os.environ), n_devices=1)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("HYPEROPT_TPU_CHAOS", None)
    if chaos_spec:
        env["HYPEROPT_TPU_CHAOS"] = chaos_spec
    return env


def main():
    from hyperopt_tpu.filestore import FileStore
    from hyperopt_tpu.parallel.driver import fmin_multihost
    from hyperopt_tpu.zoo import ZOO

    dom = ZOO["branin"]
    obj = lambda d: float(dom.objective(d))  # noqa: E731

    # undisturbed references: the collective single-process driver AND a
    # one-controller fleet on a fresh store must already agree bitwise
    ref = fmin_multihost(obj, dom.space, max_evals=MAX_EVALS, batch=BATCH,
                         seed=SEED, _force_single=True)
    with tempfile.TemporaryDirectory() as tmp:
        fleet_ref = fmin_multihost(
            obj, dom.space, max_evals=MAX_EVALS, batch=BATCH, seed=SEED,
            fleet_dir=os.path.join(tmp, "ref"), n_shards=N_SHARDS,
            lease_ttl=LEASE_TTL)
        assert fleet_ref.checksum == ref.checksum, \
            "fleet mode diverged from the collective driver UNDISTURBED"

        fleet_dir = os.path.join(tmp, "chaos")
        schedules = [
            "7:term@gen:3",      # dies mid-run with a flight dump
            "7:kill@publish:2",  # dies holding a lease: reclaim path
            None,                # clean survivor
        ]
        args = [sys.executable, CHILD, fleet_dir, "--seed", str(SEED),
                "--max-evals", str(MAX_EVALS), "--batch", str(BATCH),
                "--n-shards", str(N_SHARDS), "--lease-ttl", str(LEASE_TTL)]
        procs = [subprocess.Popen(args, env=_child_env(spec), cwd=REPO,
                                  stdout=subprocess.PIPE,
                                  stderr=subprocess.PIPE, text=True)
                 for spec in schedules]
        outs = [p.communicate(timeout=600) for p in procs]

        survivors = []
        for i, (p, (out, err)) in enumerate(zip(procs, outs)):
            if p.returncode == 0:
                assert "FLEET_OK" in out, (i, out, err[-2000:])
                survivors.append(
                    [tok.split("=", 1)[1] for tok in out.split()
                     if tok.startswith("checksum=")][0])
            else:
                # the scheduled deaths: SIGTERM (-15) / SIGKILL (-9)
                assert p.returncode in (-15, -9, 1), (i, p.returncode,
                                                      err[-2000:])
        assert survivors, (
            "every controller died — the fleet did not survive:\n"
            + "\n".join(
                f"--- child {i} (chaos={schedules[i]}) rc={p.returncode}\n"
                f"{err[-1500:]}"
                for i, (p, (out, err)) in enumerate(zip(procs, outs))))
        for c in survivors:
            assert c == ref.checksum, (
                f"chaos-run checksum {c} != undisturbed {ref.checksum}")
        print(f"chaos fleet: {len(survivors)}/3 controllers survived, "
              f"checksum converged bitwise")

        # forensics: the SIGTERM'd controller dumped its flight ring into
        # the store's attachments, injection recorded
        dumps = FileStore(fleet_dir).read_flight_dumps()
        assert dumps, "no flight dump found for the SIGTERM'd controller"
        chaos_recs = [r for recs in dumps.values() for r in recs
                      if r.get("kind") == "chaos"]
        assert chaos_recs, f"no chaos record in flight dumps {list(dumps)}"
        print(f"flight dumps collected from {sorted(dumps)} "
              f"({len(chaos_recs)} chaos injection record(s))")

        # resumed at a different size: one fresh controller replays the
        # finished store bitwise
        replay = fmin_multihost(
            obj, dom.space, max_evals=MAX_EVALS, batch=BATCH, seed=SEED,
            fleet_dir=fleet_dir, n_shards=N_SHARDS, lease_ttl=LEASE_TTL)
        assert replay.checksum == ref.checksum, "store replay diverged"
        print("post-chaos store replay: bitwise identical")

    print("CHAOS_SMOKE_OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
