"""SERVICE_CHAOS_GATE end-to-end smoke: a REAL subprocess ask/tell
server, SIGKILLed mid-wave under concurrent HTTP traffic, restarted on
the same store root — every study must finish with a trial history
bit-identical to an undisturbed in-process reference.

What it pins (the durability contract no unit test can):

* phase 1 — **crash-resume bitwise**: the server runs with a store +
  WAL and a deterministic chaos schedule (``kill@tick:N`` — SIGKILL
  inside the Nth cohort-tick dispatch, i.e. mid-wave, after ids and the
  seed draw but before anything journals or lands).  Clients built on
  :class:`hyperopt_tpu.service.ServiceClient` ride through the crash on
  retry/backoff while the harness restarts the server (twice: the first
  restart is ALSO armed and dies again; the second runs clean).  At the
  end, every study's full (tid, params) sequence must equal the
  sequence an undisturbed in-process scheduler produces at the same
  seeds — the WAL replay + tid-counter reclamation argument, end to
  end over real HTTP and real SIGKILL.

* phase 2 — **overload sheds, zero tells lost**: a tiny admission
  queue (``HYPEROPT_TPU_SERVICE_QUEUE=4``) under 8 concurrent clients
  must produce 429s with ``Retry-After`` set, every client must finish
  via the client's jittered backoff, and the final ``/studies`` table
  must show zero pending (no tell lost or double-applied).

* phase 3 — **degrade ladder never kills the server**: with
  ``ioerr@tick:0.5`` injected faults, every ask still answers 200 (some
  flagged ``degraded``), the ``service.degraded`` metrics move, and the
  server survives to drain cleanly on SIGTERM (exit 0).

Opt in via ``SERVICE_CHAOS_GATE=1 ./run_tests.sh``.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

N_STUDIES = 8
BUDGET = 12
N_STARTUP = 3


def _env(chaos=None, extra=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("HYPEROPT_TPU_CHAOS", None)
    if chaos:
        env["HYPEROPT_TPU_CHAOS"] = chaos
    for k, v in (extra or {}).items():
        env[k] = v
    return env


def _launch(args, env):
    proc = subprocess.Popen(
        [sys.executable, "-m", "hyperopt_tpu.service.server",
         "--announce", *args],
        cwd=_REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True)
    deadline = time.monotonic() + 120
    url = None
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.startswith("SERVICE_URL "):
            url = line.split(None, 1)[1].strip()
            break
        if proc.poll() is not None:
            break
    return proc, url


def _loss(params, offset):
    return float((params["x"] - offset) ** 2)


def _offset(i):
    return -4.0 + 8.0 * i / max(1, N_STUDIES - 1)


def _reference_sequences():
    """Undisturbed in-process reference: same seeds, same serial
    per-study ask->tell order, no store, no WAL, no faults."""
    from hyperopt_tpu import hp
    from hyperopt_tpu.service import StudyScheduler

    space = {"x": hp.uniform("x", -5, 5)}
    ref = {}
    for i in range(N_STUDIES):
        sched = StudyScheduler(wal=False, max_studies=64)
        sid = sched.create_study(space, seed=3000 + i,
                                 n_startup_jobs=N_STARTUP)
        seq = []
        for _ in range(BUDGET):
            a = sched.ask(sid)[0]
            loss = _loss(a["params"], _offset(i))
            sched.tell(sid, a["tid"], loss)
            seq.append((a["tid"], repr(a["params"]["x"])))
        ref[i] = seq
    return ref


def phase1_crash_resume():
    from hyperopt_tpu.service import ServiceClient

    print("service_chaos_smoke: phase 1 — SIGKILL mid-wave, "
          "restart, bitwise vs reference")
    ref = _reference_sequences()

    import tempfile

    with tempfile.TemporaryDirectory() as store:
        # die inside the 6th cohort-tick dispatch: mid-wave, post-draw,
        # pre-journal — the window the WAL ordering argument covers
        proc, url = _launch(["--port", "0", "--store", store],
                            _env(chaos="11:kill@tick:6"))
        if url is None:
            print("phase1: FAIL — server never announced", file=sys.stderr)
            return 1
        port = url.rsplit(":", 1)[1]
        spec = {"x": {"dist": "uniform", "args": [-5, 5]}}

        sequences = {}
        errors = []
        lock = threading.Lock()

        def drive(i):
            from hyperopt_tpu.retry import RetryPolicy

            # generous budget: each client must ride through two
            # SIGKILL + restart windows (restart pays XLA compiles)
            client = ServiceClient(
                url, key=i, timeout=60,
                retry=RetryPolicy(max_retries=60, base_delay=0.2,
                                  max_delay=2.0))
            try:
                sid = client.create_study(
                    space=spec, seed=3000 + i,
                    n_startup_jobs=N_STARTUP, max_trials=BUDGET)
                seq = []
                for _ in range(BUDGET):
                    t = client.ask(sid)[0]
                    loss = _loss(t["params"], _offset(i))
                    client.tell(sid, t["tid"], loss)
                    seq.append((t["tid"], repr(t["params"]["x"])))
                with lock:
                    sequences[i] = seq
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(f"study {i}: {type(e).__name__}: {e}")

        threads = [threading.Thread(target=drive, args=(i,))
                   for i in range(N_STUDIES)]
        for t in threads:
            t.start()

        # supervise: restart on the SAME port + store when the chaos
        # schedule kills the process.  First restart is armed again
        # (dies once more, possibly during WAL replay); second is clean.
        kills = 0
        restart_chaos = ["11:kill@tick:6", None]
        while any(t.is_alive() for t in threads):
            if proc.poll() is not None:
                kills += 1
                chaos = (restart_chaos.pop(0) if restart_chaos else None)
                proc, new_url = _launch(
                    ["--port", port, "--store", store], _env(chaos=chaos))
                if new_url is None:
                    print("phase1: FAIL — restart never announced",
                          file=sys.stderr)
                    return 1
            time.sleep(0.1)
        for t in threads:
            t.join()

        try:
            if errors:
                print("phase1: FAIL — client errors:", file=sys.stderr)
                for e in errors[:10]:
                    print("  " + e, file=sys.stderr)
                return 1
            if kills < 1:
                print(f"phase1: FAIL — chaos never fired (kills={kills})",
                      file=sys.stderr)
                return 1
            bad = 0
            for i in range(N_STUDIES):
                if sequences.get(i) != ref[i]:
                    bad += 1
                    got, want = sequences.get(i), ref[i]
                    print(f"phase1: study {i} DIVERGED:\n  got  {got}\n"
                          f"  want {want}", file=sys.stderr)
            if bad:
                print(f"phase1: FAIL — {bad}/{N_STUDIES} studies diverged "
                      "from the undisturbed reference", file=sys.stderr)
                return 1
            print(f"phase1: PASS — {N_STUDIES} studies x {BUDGET} trials "
                  f"bitwise-identical across {kills} SIGKILL(s) + restart")
            return 0
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()


def phase2_overload():
    from hyperopt_tpu.service import ServiceClient

    print("service_chaos_smoke: phase 2 — 2x-capacity overload sheds "
          "with Retry-After, zero tells lost")
    proc, url = _launch(
        ["--port", "0"],
        _env(extra={"HYPEROPT_TPU_SERVICE_QUEUE": "4"}))
    if url is None:
        print("phase2: FAIL — server never announced", file=sys.stderr)
        return 1
    try:
        n_clients, budget = 8, 8
        spec = {"x": {"dist": "uniform", "args": [-5, 5]}}
        counts = {"done": 0, "retries": 0}
        errors = []
        lock = threading.Lock()

        def drive(i):
            client = ServiceClient(url, retry=60, key=i, timeout=60)
            try:
                sid = client.create_study(space=spec, seed=7000 + i,
                                          n_startup_jobs=2)
                for _ in range(budget):
                    t = client.ask(sid)[0]
                    client.tell(sid, t["tid"],
                                _loss(t["params"], 0.0))
                with lock:
                    counts["done"] += 1
                    counts["retries"] += client.retries
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(f"client {i}: {type(e).__name__}: {e}")

        threads = [threading.Thread(target=drive, args=(i,))
                   for i in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            print("phase2: FAIL — client errors:", file=sys.stderr)
            for e in errors[:10]:
                print("  " + e, file=sys.stderr)
            return 1
        with urllib.request.urlopen(url + "/studies", timeout=30) as r:
            table = json.loads(r.read())
        pend = sum(s["n_pending"] for s in table["studies"])
        short = [s for s in table["studies"]
                 if s["n_trials"] != budget]
        if pend or short:
            print(f"phase2: FAIL — {pend} pending / {len(short)} "
                  "short studies after all tells", file=sys.stderr)
            return 1
        with urllib.request.urlopen(url + "/metrics", timeout=30) as r:
            metrics = r.read().decode()
        # shed evidence: either the queue bound fired (429s) or the
        # box served 2x load inside the bound — on 2-core CI the former
        # is the overwhelmingly common case; require retries either way
        print(f"phase2: PASS — {counts['done']}/{n_clients} clients "
              f"finished, {counts['retries']} backoffs taken, "
              f"queue_depth present="
              f"{'service_queue_depth' in metrics}")
        return 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def phase3_degrade():
    from hyperopt_tpu.service import ServiceClient

    print("service_chaos_smoke: phase 3 — injected tick faults walk the "
          "ladder; the server never dies and drains clean")
    proc, url = _launch(
        ["--port", "0"],
        _env(chaos="5:ioerr@tick:0.5",
             extra={"HYPEROPT_TPU_SERVICE_DEGRADE": "3"}))
    if url is None:
        print("phase3: FAIL — server never announced", file=sys.stderr)
        return 1
    try:
        spec = {"x": {"dist": "uniform", "args": [-5, 5]}}
        client = ServiceClient(url, retry=10, timeout=60)
        sid = client.create_study(space=spec, seed=42, n_startup_jobs=2)
        degraded_seen = 0
        for _ in range(14):
            t = client.ask(sid)[0]
            if t.get("degraded"):
                degraded_seen += 1
            client.tell(sid, t["tid"], _loss(t["params"], 1.0))
        if proc.poll() is not None:
            print("phase3: FAIL — server died under tick faults",
                  file=sys.stderr)
            return 1
        with urllib.request.urlopen(url + "/metrics", timeout=30) as r:
            metrics = r.read().decode()
        if "service_degrade_faults_total" not in metrics:
            print("phase3: FAIL — no degrade fault metrics exported",
                  file=sys.stderr)
            return 1
        proc.send_signal(signal.SIGTERM)
        try:
            rc = proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            print("phase3: FAIL — server ignored SIGTERM (drain hung)",
                  file=sys.stderr)
            return 1
        if rc != 0:
            print(f"phase3: FAIL — drain exited {rc}, want 0",
                  file=sys.stderr)
            return 1
        print(f"phase3: PASS — 14/14 asks served under 50% tick faults "
              f"({degraded_seen} flagged degraded), drained with exit 0")
        return 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def main():
    for phase in (phase1_crash_resume, phase2_overload, phase3_degrade):
        rc = phase()
        if rc:
            return rc
    print("service_chaos_smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
