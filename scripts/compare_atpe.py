"""aTPE-vs-TPE zoo comparison (generates the BASELINE.md table).

Run on forced CPU:

    env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu PYTHONPATH=/root/repo \
        python scripts/compare_atpe.py [--domains d1,d2] [--seeds N] [--evals N]

Prints one line per domain with mean best loss for each algo and a final
summary JSON, and appends the full table to the trajectory store as a
``kind="quality"`` record (ISSUE 16 — ``obs/quality.quality_record``;
invisible to the perf gate, which filters ``kind == "bench"``).  Disable
the append with ``--no-trajectory``.
"""

import argparse
import json
import sys

import numpy as np

from hyperopt_tpu import Trials, fmin
from hyperopt_tpu.algos import atpe, tpe
from hyperopt_tpu.zoo import ZOO

DOMAINS = ["branin", "hartmann6", "gauss_wave", "distractor", "rosenbrock4",
           "quadratic1", "hr_conditional"]


def best_loss(domain, algo, seed, max_evals):
    t = Trials()
    fmin(domain.objective, domain.space, algo=algo, max_evals=max_evals,
         trials=t, rstate=np.random.default_rng(seed), show_progressbar=False)
    return min(l for l in t.losses() if l is not None)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--domains", default=",".join(DOMAINS))
    ap.add_argument("--seeds", type=int, default=5)
    ap.add_argument("--evals", type=int, default=75)
    ap.add_argument("--no-trajectory", action="store_true",
                    help="print only; skip the kind=\"quality\" "
                         "trajectory-store append")
    args = ap.parse_args()

    rows = {}
    for name in args.domains.split(","):
        dom = ZOO[name]
        t_best = [best_loss(dom, tpe.suggest, s, args.evals)
                  for s in range(args.seeds)]
        a_best = [best_loss(dom, atpe.suggest, s, args.evals)
                  for s in range(args.seeds)]
        t_m, a_m = float(np.mean(t_best)), float(np.mean(a_best))
        span = max(abs(t_m), 1e-9)
        rows[name] = {"tpe": t_m, "atpe": a_m,
                      "atpe_wins": bool(a_m <= t_m),
                      "rel_worse": float(max(a_m - t_m, 0.0) / span)}
        print(f"{name}: tpe={t_m:.6g} atpe={a_m:.6g} "
              f"{'WIN' if a_m <= t_m else 'LOSS'}", flush=True)
    wins = sum(r["atpe_wins"] for r in rows.values())
    print(json.dumps({"wins": wins, "total": len(rows), "rows": rows},
                     indent=1), file=sys.stderr)
    if not args.no_trajectory:
        # land the table in the trajectory store (fail-open: a store
        # problem must never fail the comparison that just ran)
        try:
            from hyperopt_tpu.obs import trajectory
            from hyperopt_tpu.obs.quality import quality_record

            algos = {
                "tpe": {"mean_best_by_domain":
                        {n: r["tpe"] for n, r in rows.items()}},
                "atpe": {"mean_best_by_domain":
                         {n: r["atpe"] for n, r in rows.items()},
                         "wins": wins, "total": len(rows),
                         "rows": rows},
            }
            path = trajectory.append(quality_record(
                "scripts/compare_atpe.py", algos,
                config={"domains": sorted(rows), "seeds": args.seeds,
                        "evals": args.evals}))
            print(f"compare_atpe: appended quality record to {path}",
                  file=sys.stderr)
        except Exception as e:  # noqa: BLE001
            print(f"compare_atpe: trajectory append failed (non-fatal): "
                  f"{type(e).__name__}: {e}", file=sys.stderr)


if __name__ == "__main__":
    main()
