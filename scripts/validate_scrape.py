#!/usr/bin/env python
"""Validate the live observability plane's scrape surfaces
(hyperopt_tpu/obs/serve.py): Prometheus ``/metrics`` text and the
``/snapshot`` JSON shape.

Checked invariants — the contract a scraper actually relies on:

``/metrics`` (Prometheus text exposition):

* every non-comment line is ``name{labels} value`` with a legal metric
  name (``[a-zA-Z_:][a-zA-Z0-9_:]*``) and a float-parseable value;
* every sample's family has a preceding ``# TYPE`` line with a known type
  (``counter``/``gauge``/``summary``), counters end in ``_total``;
* label syntax parses, label values are quote/backslash/newline-escaped;
* no duplicate ``(name, labels)`` series.

``/snapshot`` (JSON):

* the four headline sections (``report``/``health``/``utilization``/
  ``ask_pipeline``) are present — the shared-serializer shape
  ``obs.report --format json`` also emits;
* ``ask_pipeline`` carries numeric ``calls``/``speculative``/``inflight``.

Exit 0 when every input validates, 1 otherwise, 2 on unreadable input.

``--self-test`` is the end-to-end CI gate (``SERVE_GATE=1
./run_tests.sh``): arm the scrape server on a short real ``fmin`` child
process, scrape ``/metrics`` + ``/snapshot`` MID-RUN, validate both, and
check the counters moved between two scrapes (monotonicity under load).
"""

from __future__ import annotations

import argparse
import json
import re
import sys

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)$")
_LABEL_RE = re.compile(
    r'(?P<key>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<val>(?:[^"\\]|\\.)*)"')
_KNOWN_TYPES = {"counter", "gauge", "summary", "histogram", "untyped"}


def validate_metrics_text(text):
    """Return a list of human-readable violations (empty = valid)."""
    errors = []
    types = {}  # family name -> declared type
    seen_series = set()
    for i, line in enumerate(text.splitlines(), 1):
        line = line.rstrip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4:
                errors.append(f"line {i}: malformed TYPE line {line!r}")
                continue
            _, _, fam, typ = parts
            if typ not in _KNOWN_TYPES:
                errors.append(f"line {i}: unknown metric type {typ!r}")
            if fam in types:
                errors.append(f"line {i}: duplicate TYPE for {fam}")
            types[fam] = typ
            continue
        if line.startswith("#"):
            continue  # HELP / comments
        m = _SAMPLE_RE.match(line)
        if not m:
            errors.append(f"line {i}: unparseable sample {line!r}")
            continue
        name, labels, value = m.group("name", "labels", "value")
        if not _NAME_RE.match(name):
            errors.append(f"line {i}: illegal metric name {name!r}")
        try:
            float(value)
        except ValueError:
            if value not in ("+Inf", "-Inf", "NaN"):
                errors.append(f"line {i}: non-numeric value {value!r}")
        if labels:
            consumed = _LABEL_RE.sub("", labels).strip(", ")
            if consumed:
                errors.append(
                    f"line {i}: unparseable label fragment {consumed!r}")
        # family resolution: strip summary/counter suffixes
        fam = name
        for suffix in ("_total", "_sum", "_count", "_bucket"):
            if fam.endswith(suffix) and fam[: -len(suffix)] in types:
                fam = fam[: -len(suffix)]
                break
        if fam not in types and name.endswith("_total"):
            # counter families declare TYPE under the base name
            base = name[: -len("_total")]
            fam = base if base in types else fam
        if fam not in types:
            errors.append(f"line {i}: sample {name!r} has no TYPE line")
        elif types.get(fam) == "counter" and not name.endswith("_total"):
            errors.append(f"line {i}: counter sample {name!r} lacks _total")
        series = (name, labels or "")
        if series in seen_series:
            errors.append(f"line {i}: duplicate series {series}")
        seen_series.add(series)
    return errors


def parse_samples(text):
    """``{(name, labels): float value}`` for monotonicity checks."""
    out = {}
    for line in text.splitlines():
        m = _SAMPLE_RE.match(line.strip())
        if m and not line.startswith("#"):
            try:
                out[(m.group("name"), m.group("labels") or "")] = float(
                    m.group("value"))
            except ValueError:
                pass
    return out


#: the blackbox-prober gauge families (ISSUE 18) a probe-armed server
#: must expose: one per `probe.*` registry gauge set every cycle.  The
#: probe smoke lints a live scrape against these; counters
#: (``hyperopt_tpu_probe_verdict_*_total``) are per-verdict-lazy, so
#: only the unconditional families are required.
PROBE_FAMILIES = (
    "hyperopt_tpu_probe_cycles",
    "hyperopt_tpu_probe_last_verdict_code",
    "hyperopt_tpu_probe_golden_match_streak",
    "hyperopt_tpu_probe_last_cycle_ts",
    "hyperopt_tpu_probe_targets",
)


def validate_probe_families(text):
    """Full exposition lint PLUS presence of every probe gauge family —
    the check a probe-armed scrape must pass (empty = valid)."""
    errors = validate_metrics_text(text)
    names = {name for name, _ in parse_samples(text)}
    for fam in PROBE_FAMILIES:
        if fam not in names:
            errors.append(f"probe-armed scrape lacks family {fam!r}")
    return errors


#: the tenant-observatory gauge families (ISSUE 20) a tenant-armed
#: server must expose after serving traffic: the ledger roll-up gauges
#: refreshed on every scrape.  Per-tenant families
#: (``hyperopt_tpu_service_tenant_<label>_*``) are table-lazy, so only
#: the unconditional roll-ups are required.
TENANT_FAMILIES = (
    "hyperopt_tpu_service_tenant_tracked",
    "hyperopt_tpu_service_tenant_evictions",
    "hyperopt_tpu_service_tenant_sheds",
)


def validate_tenant_families(text):
    """Full exposition lint PLUS presence of every tenant roll-up gauge
    family — the check a tenant-armed scrape must pass (empty =
    valid)."""
    errors = validate_metrics_text(text)
    names = {name for name, _ in parse_samples(text)}
    for fam in TENANT_FAMILIES:
        if fam not in names:
            errors.append(f"tenant-armed scrape lacks family {fam!r}")
    return errors


_SNAPSHOT_SECTIONS = ("report", "health", "utilization", "ask_pipeline")


def validate_snapshot(snap):
    """Violations in a ``/snapshot`` payload (empty = valid)."""
    errors = []
    if not isinstance(snap, dict):
        return ["snapshot is not a JSON object"]
    sections = snap.get("sections")
    if not isinstance(sections, dict):
        return ["snapshot has no 'sections' object"]
    for name in _SNAPSHOT_SECTIONS:
        if name not in sections:
            errors.append(f"sections missing {name!r}")
    ask = sections.get("ask_pipeline") or {}
    for key in ("calls", "speculative", "inflight"):
        if not isinstance(ask.get(key), (int, float)):
            errors.append(f"ask_pipeline.{key} is not numeric "
                          f"({ask.get(key)!r})")
    report = sections.get("report")
    if isinstance(report, dict):
        for phase, e in report.items():
            if not isinstance(e, dict) or "sec" not in e or "count" not in e:
                errors.append(f"report[{phase!r}] lacks sec/count")
    return errors


# ---------------------------------------------------------------------------
# end-to-end self test (the SERVE_GATE)
# ---------------------------------------------------------------------------

_CHILD = r"""
import sys, time
import numpy as np
from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu.algos import rand

url_file = sys.argv[1]
t = Trials()

state = {"n": 0, "written": False}
def objective(d):
    state["n"] += 1
    if not state["written"]:
        # the server is live once FMinIter constructed: hand the parent
        # the ephemeral URL, then keep trials slow enough to scrape mid-run
        with open(url_file + ".tmp", "w") as f:
            f.write(t.obs_http_url or "DISABLED")
        import os
        os.replace(url_file + ".tmp", url_file)
        state["written"] = True
    time.sleep(0.05)
    return (d["x"] - 1.0) ** 2

fmin(objective, {"x": hp.uniform("x", -5, 5)}, algo=rand.suggest,
     max_evals=60, trials=t, rstate=np.random.default_rng(0),
     show_progressbar=False, obs_http=0)
print("CHILD_DONE")
"""


def _self_test():
    """Arm a real child fmin with the scrape server, validate mid-run."""
    import os
    import subprocess
    import tempfile
    import time
    import urllib.request

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    with tempfile.TemporaryDirectory() as d:
        url_file = os.path.join(d, "url")
        proc = subprocess.Popen([sys.executable, "-c", _CHILD, url_file],
                                env=env, cwd=repo,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True)
        try:
            deadline = time.time() + 120
            while not os.path.exists(url_file):
                if proc.poll() is not None or time.time() > deadline:
                    out, err = proc.communicate(timeout=10)
                    print("self-test: child died before serving:\n"
                          + err[-2000:], file=sys.stderr)
                    return 1
                time.sleep(0.05)
            with open(url_file) as f:
                url = f.read().strip()
            if url == "DISABLED":
                print("self-test: server failed open in the child",
                      file=sys.stderr)
                return 1

            def get(path):
                with urllib.request.urlopen(url + path, timeout=10) as r:
                    return r.read().decode()

            # wait for the first landed trial: the url file is written
            # DURING the first evaluation, before any counter increments
            while True:
                snap = json.loads(get("/snapshot"))
                if snap.get("trials_completed", 0) >= 1:
                    break
                if time.time() > deadline:
                    print("self-test: no trial ever completed",
                          file=sys.stderr)
                    return 1
                time.sleep(0.05)
            text1 = get("/metrics")
            errors = validate_metrics_text(text1)
            errors += validate_snapshot(snap)
            time.sleep(0.5)
            text2 = get("/metrics")
            errors += validate_metrics_text(text2)
            # counters must be monotone non-decreasing between scrapes
            s1, s2 = parse_samples(text1), parse_samples(text2)
            moved = False
            for series, v1 in s1.items():
                if not series[0].endswith("_total"):
                    continue
                v2 = s2.get(series)
                if v2 is None:
                    continue
                if v2 < v1:
                    errors.append(f"counter {series} went backwards "
                                  f"({v1} -> {v2})")
                if v2 > v1:
                    moved = True
            if not moved:
                errors.append("no counter advanced between two mid-run "
                              "scrapes — is the run actually live?")
            if errors:
                print("self-test: scrape INVALID:", file=sys.stderr)
                for e in errors:
                    print("  " + e, file=sys.stderr)
                return 1
            n_series = len(parse_samples(text2))
            print(f"self-test OK: {n_series} series lint clean, snapshot "
                  "sections present, counters monotone under load")
            return 0
        finally:
            try:
                proc.wait(timeout=120)
            except subprocess.TimeoutExpired:
                proc.kill()


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python scripts/validate_scrape.py",
        description="Validate /metrics (Prometheus text) and /snapshot "
                    "(JSON) scrape payloads.")
    p.add_argument("files", nargs="*",
                   help="payload file(s): *.json validates as a snapshot, "
                        "anything else as Prometheus text")
    p.add_argument("--self-test", action="store_true",
                   help="arm the server on a short real fmin and validate "
                        "a mid-run scrape end to end (the CI gate)")
    p.add_argument("--require-probe", action="store_true",
                   help="additionally require the hyperopt_tpu_probe_* "
                        "gauge families in every metrics payload (a "
                        "probe-armed server's scrape contract)")
    p.add_argument("--require-tenant", action="store_true",
                   help="additionally require the "
                        "hyperopt_tpu_service_tenant_* roll-up gauge "
                        "families in every metrics payload (a "
                        "tenant-armed server's scrape contract)")
    args = p.parse_args(argv)
    if args.self_test:
        return _self_test()
    if not args.files:
        p.error("give payload file(s) or --self-test")
    rc = 0
    for path in args.files:
        try:
            with open(path) as f:
                body = f.read()
        except OSError as e:
            print(f"{path}: cannot read ({e})", file=sys.stderr)
            return 2
        if path.endswith(".json"):
            try:
                errors = validate_snapshot(json.loads(body))
            except ValueError as e:
                errors = [f"not JSON: {e}"]
        else:
            errors = validate_metrics_text(body)
            names = None
            if args.require_probe or args.require_tenant:
                names = {name for name, _ in parse_samples(body)}
            if args.require_probe:
                errors += [f"probe-armed scrape lacks family {fam!r}"
                           for fam in PROBE_FAMILIES if fam not in names]
            if args.require_tenant:
                errors += [f"tenant-armed scrape lacks family {fam!r}"
                           for fam in TENANT_FAMILIES if fam not in names]
        if errors:
            rc = 1
            print(f"{path}: INVALID")
            for e in errors:
                print("  " + e)
        else:
            print(f"{path}: ok")
    return rc


if __name__ == "__main__":
    sys.exit(main())
