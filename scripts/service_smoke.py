"""SERVICE_GATE end-to-end smoke: a REAL subprocess ask/tell server under
100 concurrent HTTP studies driven to convergence.

What it pins (the serving contract no unit test can):

* the server binds as a real subprocess (``python -m
  hyperopt_tpu.service.server --port 0 --announce``) and the handshake
  (``SERVICE_URL <url>``) works;
* 100 concurrent studies — heterogeneous quadratic spaces — each drive a
  full ask→evaluate→tell loop over HTTP from a thread pool, and the
  optimizer CONVERGES (TPE beats the prior: the median best loss across
  studies must clear a bar random search at the same budget does not);
* ``GET /studies`` answers a table consistent with what the clients did
  (validated field-by-field, ``scripts/validate_scrape.py`` style);
* ``GET /metrics`` passes the Prometheus exposition lint and carries the
  ``service.*`` family;
* the server dies cleanly on SIGTERM.

Opt in via ``SERVICE_GATE=1 ./run_tests.sh``.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

N_STUDIES = 100
BUDGET = 24
N_STARTUP = 5
N_WORKERS = 12
# quadratic1-family objective with per-study offset: min 0 at x = offset.
# Prior best-of-24 over U(-5,5) has median |x-c| ~ 0.29 -> loss ~ 0.085;
# TPE reliably lands well under this; a broken posterior does not.
CONVERGENCE_BAR = 0.25


def _get(url, path, timeout=60):
    with urllib.request.urlopen(url + path, timeout=timeout) as r:
        return r.status, r.read()


def validate_studies_payload(payload, expect_ids):
    """Field-by-field lint of the ``GET /studies`` table (the
    validate_scrape.py doctrine: structural invariants, not magic
    values).  Returns a list of error strings."""
    errs = []
    for key in ("ts", "n_studies", "slot_utilization", "cohorts",
                "studies", "cohort_cache"):
        if key not in payload:
            errs.append(f"/studies missing key {key!r}")
    if errs:
        return errs
    if payload["n_studies"] != len(payload["studies"]):
        errs.append("n_studies != len(studies)")
    if not 0.0 <= payload["slot_utilization"] <= 1.0:
        errs.append(f"slot_utilization out of [0,1]: "
                    f"{payload['slot_utilization']}")
    by_id = {}
    for s in payload["studies"]:
        for key in ("study_id", "state", "n_trials", "n_pending",
                    "best_loss", "labels"):
            if key not in s:
                errs.append(f"study entry missing {key!r}")
        by_id[s.get("study_id")] = s
        if s.get("n_pending", 0) != 0:
            errs.append(f"{s.get('study_id')}: {s['n_pending']} pending "
                        "after all tells")
    for sid, want_trials in expect_ids.items():
        s = by_id.get(sid)
        if s is None:
            errs.append(f"{sid} missing from /studies")
        elif s["n_trials"] != want_trials:
            errs.append(f"{sid}: n_trials {s['n_trials']} != {want_trials}")
    for c in payload["cohorts"]:
        if c.get("n_live", 0) > c.get("n_slots", 0):
            errs.append(f"cohort overfull: {c}")
    cache = payload["cohort_cache"]
    if cache.get("misses", 0) <= 0:
        errs.append("cohort cache never compiled anything?")
    return errs


def main():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "hyperopt_tpu.service.server",
         "--port", "0", "--announce", "--max-studies", "256"],
        cwd=_REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    url = None
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if line.startswith("SERVICE_URL "):
                url = line.split(None, 1)[1].strip()
                break
            if proc.poll() is not None:
                break
        if url is None:
            print("service_smoke: FAIL — server never announced",
                  file=sys.stderr)
            print((proc.stderr.read() or "")[-2000:], file=sys.stderr)
            return 1
        print(f"service_smoke: server up at {url} (pid {proc.pid})")

        results = {}   # sid -> (n_trials, best_loss)
        errors = []
        lock = threading.Lock()
        work = list(range(N_STUDIES))

        from hyperopt_tpu.service import ServiceClient

        def drive():
            # the retry-aware client (service/client.py): 429/503 +
            # Retry-After and connection resets are honored with
            # deterministic jittered backoff — no ad-hoc sleep loops
            client = ServiceClient(url, retry=8,
                                   key=threading.get_ident())
            while True:
                with lock:
                    if not work:
                        return
                    i = work.pop()
                offset = -4.0 + 8.0 * i / (N_STUDIES - 1)
                try:
                    sid = client.create_study(
                        space={"x": {"dist": "uniform", "args": [-5, 5]}},
                        seed=1000 + i, n_startup_jobs=N_STARTUP,
                        max_trials=BUDGET)
                    best = float("inf")
                    for _ in range(BUDGET):
                        t = client.ask(sid)[0]
                        loss = (t["params"]["x"] - offset) ** 2
                        best = min(best, loss)
                        client.tell(sid, t["tid"], loss)
                    with lock:
                        results[sid] = (BUDGET, best)
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errors.append(f"study {i}: {type(e).__name__}: {e}")

        t0 = time.perf_counter()
        threads = [threading.Thread(target=drive) for _ in range(N_WORKERS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        if errors:
            print("service_smoke: FAIL — client errors:", file=sys.stderr)
            for e in errors[:10]:
                print("  " + e, file=sys.stderr)
            return 1
        bests = sorted(b for _, b in results.values())
        median_best = bests[len(bests) // 2]
        print(f"service_smoke: {N_STUDIES} studies x {BUDGET} trials over "
              f"HTTP in {dt:.1f}s ({N_STUDIES * BUDGET / dt:.0f} "
              f"asks/sec), median best loss {median_best:.4f}")
        if median_best > CONVERGENCE_BAR:
            print(f"service_smoke: FAIL — median best loss {median_best} "
                  f"> {CONVERGENCE_BAR} (optimizer did not converge)",
                  file=sys.stderr)
            return 1

        code, body = _get(url, "/studies")
        assert code == 200
        payload = json.loads(body)
        errs = validate_studies_payload(
            payload, {sid: n for sid, (n, _) in results.items()})
        if errs:
            print("service_smoke: FAIL — /studies lint:", file=sys.stderr)
            for e in errs[:10]:
                print("  " + e, file=sys.stderr)
            return 1
        print(f"service_smoke: /studies lint ok "
              f"({payload['n_studies']} studies, "
              f"util {payload['slot_utilization']:.2f}, "
              f"cache {payload['cohort_cache']})")

        code, body = _get(url, "/metrics")
        assert code == 200
        text = body.decode()
        from validate_scrape import validate_metrics_text

        lint = validate_metrics_text(text)
        if lint:
            print("service_smoke: FAIL — /metrics lint:", file=sys.stderr)
            for e in lint[:10]:
                print("  " + e, file=sys.stderr)
            return 1
        if "hyperopt_tpu_service_asks_total" not in text:
            print("service_smoke: FAIL — service.* family missing from "
                  "/metrics", file=sys.stderr)
            return 1
        print("service_smoke: /metrics exposition lint ok")

        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            print("service_smoke: FAIL — server ignored SIGTERM",
                  file=sys.stderr)
            return 1
        print("service_smoke: PASS")
        return 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


if __name__ == "__main__":
    sys.exit(main())
