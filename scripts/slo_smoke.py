"""SLO_GATE end-to-end smoke (ISSUE 11): request tracing + audit
timeline + SLO plane against a REAL subprocess server.

What it pins (the cross-process correlation no in-process test can):

* a real ``python -m hyperopt_tpu.service.server`` subprocess with WAL
  store, access log, SLO plane and tracing armed;
* ONE traced ``ServiceClient`` ask: the trace id the client minted comes
  back on the response AND lands in the WAL ask record on disk AND in
  the ``GET /study/<id>/timeline`` payload — the cross-process slice of
  the five-layer correlation pin (the in-process layers are tier-1,
  tests/test_timeline.py);
* ``GET /metrics`` passes the Prometheus exposition lint and carries the
  ``hyperopt_tpu_slo_*`` gauge families;
* ``obs.report --study <id>`` renders the complete timeline from the
  store (run against the live WAL, before drain-time compaction
  collapses history into a snapshot);
* the access log holds one JSONL record per request, trace ids included;
* the server still drains cleanly on SIGTERM (exit 0).

Opt in via ``SLO_GATE=1 ./run_tests.sh``.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

SPACE_SPEC = {"x": {"dist": "uniform", "args": [-5, 5]}}


def fail(msg):
    print(f"slo_smoke: FAIL — {msg}", file=sys.stderr)
    return 1


def main():
    from validate_scrape import validate_metrics_text

    from hyperopt_tpu.service.client import ServiceClient

    tmp = tempfile.mkdtemp(prefix="slo_smoke_")
    store = os.path.join(tmp, "store")
    access_log = os.path.join(tmp, "access.jsonl")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["HYPEROPT_TPU_SERVICE_ACCESS_LOG"] = access_log
    env["HYPEROPT_TPU_SERVICE_SLO"] = "on"
    env["HYPEROPT_TPU_REQTRACE"] = "on"
    proc = subprocess.Popen(
        [sys.executable, "-m", "hyperopt_tpu.service.server",
         "--port", "0", "--announce", "--store", store],
        cwd=_REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    url = None
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if line.startswith("SERVICE_URL "):
                url = line.split(None, 1)[1].strip()
                break
            if proc.poll() is not None:
                break
        if url is None:
            print((proc.stderr.read() or "")[-2000:], file=sys.stderr)
            return fail("server never announced")
        print(f"slo_smoke: server up at {url} (pid {proc.pid})")

        client = ServiceClient(url, trace=True)
        sid = client.create_study(space=SPACE_SPEC, seed=5,
                                  n_startup_jobs=1)
        # startup rand ask + tell, then THE traced TPE ask
        t = client.ask(sid)[0]
        client.tell(sid, t["tid"], loss=0.25)
        trials = client.ask(sid)
        trace = client.last_trace
        if not (isinstance(trace, str) and len(trace) == 32):
            return fail(f"client minted no trace id: {trace!r}")
        print(f"slo_smoke: traced ask served (trace {trace[:16]}..)")

        # layer: the WAL ask record on disk carries the trace id
        from hyperopt_tpu.service.journal import StudyJournal, wal_path_for

        wal_recs = list(StudyJournal(wal_path_for(store)).records())
        tpe_asks = [r for r in wal_recs if r.get("kind") == "ask"
                    and r.get("algo") == "tpe"]
        if not tpe_asks or tpe_asks[-1].get("trace") != trace:
            return fail(f"WAL ask record not stamped with {trace[:16]}..: "
                        f"{tpe_asks[-1] if tpe_asks else None}")

        # layer: the live timeline endpoint shows the same id
        import urllib.request

        with urllib.request.urlopen(f"{url}/study/{sid}/timeline",
                                    timeout=30) as r:
            tl = json.loads(r.read())
        tl_asks = [e for e in tl.get("events", [])
                   if e.get("event") == "ask" and e.get("algo") == "tpe"]
        if not tl_asks or tl_asks[-1].get("trace") != trace:
            return fail("timeline endpoint missing the traced ask")

        # obs.report --study reconstructs the timeline from the store
        rep = subprocess.run(
            [sys.executable, "-m", "hyperopt_tpu.obs.report",
             "--study", sid, store],
            cwd=_REPO, env=env, capture_output=True, text=True,
            timeout=120)
        if rep.returncode != 0:
            return fail(f"obs.report --study failed: {rep.stderr[-500:]}")
        if trace[:16] not in rep.stdout or "algo=tpe" not in rep.stdout:
            return fail("obs.report --study did not render the traced "
                        f"ask:\n{rep.stdout[-800:]}")
        print("slo_smoke: obs.report --study renders the traced timeline")

        # /metrics: exposition lint + the slo_* gauge families
        with urllib.request.urlopen(url + "/metrics", timeout=30) as r:
            text = r.read().decode()
        errs = validate_metrics_text(text)
        if errs:
            return fail("exposition lint: " + "; ".join(errs[:5]))
        for fam in ("hyperopt_tpu_slo_availability_budget_remaining_frac",
                    "hyperopt_tpu_slo_ask_latency_burn_fast",
                    "hyperopt_tpu_slo_shed_rate_burn_slow"):
            if fam not in text:
                return fail(f"/metrics missing slo family {fam}")
        print("slo_smoke: /metrics lints clean with slo_* gauges")

        # the access log: one record per request, trace ids throughout
        with open(access_log) as f:
            acc = [json.loads(ln) for ln in f if ln.strip()]
        posts = [a for a in acc if a.get("method") == "POST"]
        if len(posts) < 4:  # study + ask + tell + ask
            return fail(f"access log has {len(posts)} POST records, "
                        "expected >= 4")
        if not all(len(a.get("trace") or "") == 32 for a in posts):
            return fail("access-log records missing trace ids")
        if trace not in {a.get("trace") for a in posts}:
            return fail("the traced ask never hit the access log")
        print(f"slo_smoke: access log carries {len(acc)} records with "
              "trace ids")

        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
        if rc != 0:
            return fail(f"server exited {rc} on SIGTERM")
        print("slo_smoke: OK — traced ask correlated across client, WAL, "
              "timeline, report and access log; slo_* gauges lint clean; "
              "clean drain")
        return 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()


if __name__ == "__main__":
    sys.exit(main())
