"""SHARD_GATE smoke: forced-8-device sharded-equivalence + scaling check.

Run via ``SHARD_GATE=1 ./run_tests.sh`` (or directly).  Re-executes itself
in a clean subprocess pinned to 8 virtual CPU devices (the ambient env may
carry a TPU-tunnel plugin whose broken backend-init hangs uncatchably —
``hyperopt_tpu._env.forced_cpu_env``), then checks, end to end through the
public ``tpe.suggest`` path:

1. **Equivalence pin** — at the same seed, mesh-sharded proposals are
   BIT-IDENTICAL to the single-chip program for mesh shapes {1, 2, 4, 8},
   with the history axis both replicated and force-sharded
   (``HYPEROPT_TPU_HIST_SHARD_MIN`` driven below cap).
2. **Scaling smoke** — the 8-shard fused program completes a wide
   candidate batch and its measured candidates/sec is printed (shape, not
   absolute perf: CPU mesh).

Exit 0 on success; any mismatch prints the differing proposals and exits 1.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys


def _child():
    import time

    import numpy as np

    from hyperopt_tpu import Trials, fmin, hp
    from hyperopt_tpu.algos import rand, tpe
    from hyperopt_tpu.base import Domain

    space = {"x": hp.uniform("x", -5, 5),
             "lr": hp.loguniform("lr", -4, 0),
             "k": hp.randint("k", 4)}

    def obj(d):
        return (d["x"] - 1.0) ** 2 + d["lr"]

    def populated(n=10):
        t = Trials()
        fmin(obj, space, algo=rand.suggest, max_evals=n, trials=t,
             rstate=np.random.default_rng(0), show_progressbar=False)
        return t

    def proposals(n_ids=8):
        t = populated()
        dom = Domain(obj, space)
        docs = tpe.suggest(t.new_trial_ids(n_ids), dom, t, 42,
                           n_startup_jobs=5, n_EI_candidates=64)
        return [d["misc"]["vals"] for d in docs]

    os.environ.pop("HYPEROPT_TPU_SHARD", None)
    ref = proposals()
    failures = 0
    for shards in (1, 2, 4, 8):
        for hist_min in (None, "128"):  # replicated / force-sharded history
            os.environ["HYPEROPT_TPU_SHARD"] = str(shards)
            if hist_min is None:
                os.environ.pop("HYPEROPT_TPU_HIST_SHARD_MIN", None)
            else:
                os.environ["HYPEROPT_TPU_HIST_SHARD_MIN"] = hist_min
            got = proposals()
            tag = (f"shards={shards} "
                   f"hist={'sharded' if hist_min else 'replicated'}")
            if got == ref:
                print(f"  OK  {tag}: bit-identical to single-chip")
            else:
                failures += 1
                print(f"  FAIL {tag}: proposals diverged\n"
                      f"    ref {ref[0]}\n    got {got[0]}")
    os.environ.pop("HYPEROPT_TPU_HIST_SHARD_MIN", None)

    # scaling smoke: a wide sharded candidate batch completes and reports
    os.environ["HYPEROPT_TPU_SHARD"] = "8"
    t = populated()
    dom = Domain(obj, space)
    B, n_cand = 64, 256
    tpe.suggest(t.new_trial_ids(B), dom, t, 1, n_startup_jobs=5,
                n_EI_candidates=n_cand, ei_select="softmax")  # compile
    t0 = time.perf_counter()
    tpe.suggest(t.new_trial_ids(B), dom, t, 2, n_startup_jobs=5,
                n_EI_candidates=n_cand, ei_select="softmax")
    dt = time.perf_counter() - t0
    print(json.dumps({"smoke": "sharded_suggest", "shards": 8, "batch": B,
                      "n_EI_candidates": n_cand,
                      "sharded_cand_per_sec": B * n_cand / dt}))
    if failures:
        print(f"shard smoke: {failures} equivalence failure(s)")
        return 1
    print("shard smoke: ok")
    return 0


def main():
    if os.environ.get("_SHARD_SMOKE_CHILD") == "1":
        return _child()
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from hyperopt_tpu._env import forced_cpu_env

    env = forced_cpu_env(os.environ, n_devices=8)
    env["_SHARD_SMOKE_CHILD"] = "1"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                          env=env, timeout=900,
                          cwd=os.path.dirname(os.path.dirname(
                              os.path.abspath(__file__))))
    return proc.returncode


if __name__ == "__main__":
    sys.exit(main())
