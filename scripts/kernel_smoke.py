"""KERNEL_GATE end-to-end smoke (ISSUE 19): the quantized-history
fused-suggest megakernel against a REAL subprocess server.

What it pins (the cross-process slice no in-process test can):

* DISARMED IS FREE, directly: an in-process scheduler with
  ``HYPEROPT_TPU_MEGAKERNEL=0`` proposes bit-identically to one with the
  variable unset, and driving the disarmed scheduler spawns ZERO new
  threads (the kernel plane must not exist when off);
* DISARMED IS FREE, over the wire: a subprocess server with
  ``HYPEROPT_TPU_MEGAKERNEL=0`` serves a zoo mix with proposal streams
  byte-identical (full float round-trip through JSON) to a server with
  the variable unset, study for study, trial for trial;
* THE ARMED SERVER SERVES: a subprocess server with
  ``HYPEROPT_TPU_MEGAKERNEL`` armed (``interpret`` emulation on CPU —
  same fused program, XLA-executed) drives the same zoo mix to budget
  with every loss finite, ``/metrics`` lints clean and carries the
  ``hyperopt_tpu_suggest_megakernel`` gauge at 1 (the fused cohort
  really ticked), and the server drains cleanly on SIGTERM (exit 0).

Opt in via ``KERNEL_GATE=1 ./run_tests.sh``.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

#: 2 studies keeps the three-server smoke to the cheapest analytic
#: domains (quadratic1 budget 20, branin budget 30)
_MIX_N = 2


def fail(msg):
    print(f"kernel_smoke: FAIL — {msg}", file=sys.stderr)
    return 1


def _drive_mix(client, items, zoo):
    """Create + ask/tell every mix study to budget; return the proposal
    stream per study name (the exact params dicts off the wire)."""
    sids, streams = {}, {}
    for m in items:
        sids[m.name] = client.create_study(
            zoo=m.domain.name, seed=m.seed,
            n_startup_jobs=m.n_startup_jobs)
    for m in items:
        stream = []
        for _ in range(m.budget):
            t = client.ask(sids[m.name])[0]
            stream.append(t["params"])
            loss = float(zoo[m.domain.name].objective(t["params"]))
            if not (loss == loss and abs(loss) != float("inf")):
                raise AssertionError(f"non-finite loss {loss} on {m.name}")
            client.tell(sids[m.name], t["tid"], loss=loss)
        streams[m.name] = stream
    return streams


def _server(env_extra, store):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("HYPEROPT_TPU_MEGAKERNEL", None)
    env.update(env_extra)
    proc = subprocess.Popen(
        [sys.executable, "-m", "hyperopt_tpu.service.server",
         "--port", "0", "--announce", "--store", store],
        cwd=_REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    url, deadline = None, time.monotonic() + 120
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.startswith("SERVICE_URL "):
            url = line.split(None, 1)[1].strip()
            break
        if proc.poll() is not None:
            break
    if url is None:
        err = (proc.stderr.read() or "")[-2000:]
        proc.kill()
        proc.communicate()
        raise RuntimeError(f"server never announced: {err}")
    return proc, url


def _stop(proc):
    if proc.poll() is None:
        proc.kill()
        proc.communicate()


def main():
    from validate_scrape import validate_metrics_text

    from hyperopt_tpu.service.client import ServiceClient
    from hyperopt_tpu.zoo import ZOO, make_study_mix

    items = make_study_mix(_MIX_N, 0)

    # -- pin 1: disarmed == armed-off, directly + zero new threads --------
    import threading

    from hyperopt_tpu.service.scheduler import StudyScheduler

    def direct_stream(megakernel_env):
        prev = os.environ.pop("HYPEROPT_TPU_MEGAKERNEL", None)
        if megakernel_env is not None:
            os.environ["HYPEROPT_TPU_MEGAKERNEL"] = megakernel_env
        try:
            sched = StudyScheduler(wal=False)
            out = {}
            for m in items:
                sid = sched.create_study(m.domain.space, seed=m.seed,
                                         n_startup_jobs=m.n_startup_jobs)
                stream = []
                for _ in range(m.budget):
                    a = sched.ask_many([(sid, 1)])[sid][0]
                    stream.append(a["params"])
                    sched.tell(sid, a["tid"],
                               float(m.domain.objective(a["params"])))
                out[m.name] = stream
            return out
        finally:
            os.environ.pop("HYPEROPT_TPU_MEGAKERNEL", None)
            if prev is not None:
                os.environ["HYPEROPT_TPU_MEGAKERNEL"] = prev

    threads_before = threading.active_count()
    unset = direct_stream(None)
    if threading.active_count() != threads_before:
        return fail("disarmed scheduler drive changed the thread count "
                    f"({threads_before} -> {threading.active_count()})")
    armed_off = direct_stream("0")
    if unset != armed_off:
        return fail("MEGAKERNEL=0 proposals diverge from unset (direct)")
    print("kernel_smoke: disarmed == armed-off bit-identical (direct), "
          "zero new threads")

    # -- pin 2 + 3: the three subprocess servers --------------------------
    tmp = tempfile.mkdtemp(prefix="kernel_smoke_")
    proc_a, url_a = _server({}, os.path.join(tmp, "store_unset"))
    try:
        base = _drive_mix(ServiceClient(url_a), items, ZOO)
    finally:
        _stop(proc_a)
    print(f"kernel_smoke: baseline server served {len(base)} studies")

    proc_b, url_b = _server({"HYPEROPT_TPU_MEGAKERNEL": "0"},
                            os.path.join(tmp, "store_off"))
    try:
        off = _drive_mix(ServiceClient(url_b), items, ZOO)
    finally:
        _stop(proc_b)
    if off != base:
        return fail("MEGAKERNEL=0 proposals diverge from unset over HTTP")
    print("kernel_smoke: disarmed == armed-off bit-identical over HTTP")

    proc_c, url_c = _server({"HYPEROPT_TPU_MEGAKERNEL": "interpret"},
                            os.path.join(tmp, "store_armed"))
    try:
        armed = _drive_mix(ServiceClient(url_c), items, ZOO)
        for m in items:
            if len(armed[m.name]) != m.budget:
                return fail(f"armed server served {len(armed[m.name])} "
                            f"asks for {m.name}, wanted {m.budget}")

        import urllib.request

        with urllib.request.urlopen(url_c + "/metrics", timeout=30) as r:
            text = r.read().decode()
        errs = validate_metrics_text(text)
        if errs:
            return fail("armed /metrics lint: " + "; ".join(errs[:5]))
        gauge = [ln for ln in text.splitlines()
                 if ln.startswith("hyperopt_tpu_suggest_megakernel{")]
        if not gauge or not any(ln.rsplit(None, 1)[1] == "1.0"
                                for ln in gauge):
            return fail("armed server never reported "
                        f"suggest.megakernel=1: {gauge}")
        print("kernel_smoke: armed server served the mix, megakernel "
              "gauge=1, /metrics lints clean")

        proc_c.send_signal(signal.SIGTERM)
        rc = proc_c.wait(timeout=120)
        if rc != 0:
            return fail(f"armed server exited {rc} on SIGTERM")
    finally:
        _stop(proc_c)
    print("kernel_smoke: OK — disarmed byte-identical (direct + HTTP, "
          "zero new threads); armed server served the zoo mix and "
          "drained cleanly on SIGTERM")
    return 0


if __name__ == "__main__":
    sys.exit(main())
