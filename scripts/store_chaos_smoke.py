"""STORE_GATE end-to-end smoke (ISSUE 15): a REAL subprocess ask/tell
server under concurrent clients with chaos-injected WAL corruption and
disk-full faults — the storage-integrity survival contract no unit test
can pin:

* phase 1 — **corruption quarantines, never crashes**: the server runs
  with a store + WAL and ``corrupt@wal:<p>`` armed (seeded bit-flips on
  just-written records — the write succeeds, the medium lies).
  Concurrent clients drive every study to budget; the server drains
  clean.  Then: ``scrub`` must report EVERY injected corruption (count
  ground-truthed by the chaos counter scraped from /metrics — no false
  negatives), a chaos-free restart on the same root must come up
  serving (never a crash loop) with the corrupt studies quarantined
  (410 + flagged in /studies + timeline event) and every healthy study
  intact: zero acknowledged tells lost (n_pending==0, full trial
  count) and further asks bit-identical to an undisturbed in-process
  reference.  Finally ``scrub --repair`` exits 0 and the repaired
  store boots clean.

* phase 2 — **ENOSPC sheds typed and recovers**: with
  ``enospc@wal:<p>`` armed, asks that hit the full "disk" answer 507
  with ``Retry-After`` (observed raw), the store-full latch sheds and
  then re-probes, and every retrying client finishes its budget — the
  shed-then-recover loop, end to end over real HTTP.

Opt in via ``STORE_GATE=1 ./run_tests.sh``.
"""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

N_STUDIES = 8
BUDGET = 8
EXTRA = 4  # post-restart rounds pinning bitwise continuation
N_STARTUP = 3
CORRUPT_P = 0.02


def _env(chaos=None, extra=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("HYPEROPT_TPU_CHAOS", None)
    if chaos:
        env["HYPEROPT_TPU_CHAOS"] = chaos
    for k, v in (extra or {}).items():
        env[k] = v
    return env


def _launch(args, env):
    proc = subprocess.Popen(
        [sys.executable, "-m", "hyperopt_tpu.service.server",
         "--announce", *args],
        cwd=_REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True)
    deadline = time.monotonic() + 120
    url = None
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.startswith("SERVICE_URL "):
            url = line.split(None, 1)[1].strip()
            break
        if proc.poll() is not None:
            break
    return proc, url


def _get(url, path):
    with urllib.request.urlopen(url + path, timeout=30) as r:
        return r.read().decode()


def _metric(text, name):
    m = re.search(rf"^{re.escape(name)}(?:{{[^}}]*}})?\s+([0-9.eE+-]+)$",
                  text, re.M)
    return float(m.group(1)) if m else 0.0


def _loss(params, offset):
    return float((params["x"] - offset) ** 2)


def _offset(i):
    return -4.0 + 8.0 * i / max(1, N_STUDIES - 1)


def _reference_sequences(rounds):
    from hyperopt_tpu import hp
    from hyperopt_tpu.service import StudyScheduler

    space = {"x": hp.uniform("x", -5, 5)}
    ref = {}
    for i in range(N_STUDIES):
        sched = StudyScheduler(wal=False, max_studies=64)
        sid = sched.create_study(space, seed=5000 + i,
                                 n_startup_jobs=N_STARTUP)
        seq = []
        for _ in range(rounds):
            a = sched.ask(sid)[0]
            sched.tell(sid, a["tid"], _loss(a["params"], _offset(i)))
            seq.append((a["tid"], repr(a["params"]["x"])))
        ref[i] = seq
    return ref


def phase1_corruption(store):
    from hyperopt_tpu.service import ServiceClient

    print("store_chaos_smoke: phase 1 — seeded WAL bit-flips: "
          "quarantine-not-crash, scrub finds 100%, healthy bitwise")
    ref = _reference_sequences(BUDGET + EXTRA)
    spec = {"x": {"dist": "uniform", "args": [-5, 5]}}

    proc, url = _launch(["--port", "0", "--store", store],
                        _env(chaos=f"23:corrupt@wal:{CORRUPT_P}"))
    if url is None:
        print("phase1: FAIL — server never announced", file=sys.stderr)
        return 1
    port = url.rsplit(":", 1)[1]
    sequences = {}
    study_ids = {}
    errors = []
    lock = threading.Lock()

    def drive(i):
        client = ServiceClient(url, key=i, retry=20, timeout=60)
        try:
            sid = client.create_study(space=spec, seed=5000 + i,
                                      n_startup_jobs=N_STARTUP)
            seq = []
            for _ in range(BUDGET):
                t = client.ask(sid)[0]
                client.tell(sid, t["tid"],
                            _loss(t["params"], _offset(i)))
                seq.append((t["tid"], repr(t["params"]["x"])))
            with lock:
                sequences[i] = seq
                study_ids[i] = sid
        except Exception as e:  # noqa: BLE001
            with lock:
                errors.append(f"study {i}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=drive, args=(i,))
               for i in range(N_STUDIES)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    try:
        if errors:
            print("phase1: FAIL — client errors under corruption "
                  "(writes must SUCCEED; the lie surfaces at replay):",
                  file=sys.stderr)
            for e in errors[:10]:
                print("  " + e, file=sys.stderr)
            return 1
        injected = int(_metric(_get(url, "/metrics"),
                               "hyperopt_tpu_chaos_corrupt_wal_total"))
        if injected < 1:
            print(f"phase1: FAIL — chaos never corrupted a record "
                  f"(injected={injected}); raise CORRUPT_P",
                  file=sys.stderr)
            return 1
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
        if rc != 0:
            print(f"phase1: FAIL — drain exited {rc} under corruption, "
                  "want 0 (quarantine-not-crash)", file=sys.stderr)
            return 1
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    # -- scrub must report every injection (tail caveat: a flip in the
    # final line is indistinguishable from a torn tail BY DESIGN — it
    # is still reported, as the torn finding the repair truncates) -----
    from hyperopt_tpu.service import scrub as scrub_mod

    report = scrub_mod.scan_store(store)
    found = sum(w["counts"]["corrupt"] for w in report["wals"])
    torn = sum(w["counts"]["torn"] for w in report["wals"])
    if found + torn < injected or found < injected - 1:
        print(f"phase1: FAIL — scrub found {found} corrupt + {torn} "
              f"torn of {injected} injected (false negatives!)",
              file=sys.stderr)
        return 1
    print(f"phase1: scrub detected {found} corrupt (+{torn} torn-tail) "
          f"of {injected} injected — no false negatives")

    # -- chaos-free restart: quarantine, never a crash loop ------------
    proc, url = _launch(["--port", port, "--store", store], _env())
    if url is None:
        print("phase1: FAIL — restart on the corrupt store never "
              "announced (crash loop?)", file=sys.stderr)
        return 1
    try:
        table = json.loads(_get(url, "/studies"))
        by_sid = {s["study_id"]: s for s in table["studies"]}
        quarantined = {sid for sid, s in by_sid.items()
                       if s.get("state") == "quarantined"}
        if found >= 1 and not quarantined:
            print("phase1: FAIL — corrupt records found but no study "
                  "quarantined", file=sys.stderr)
            return 1
        # 410 semantics + timeline event on a quarantined study
        for sid in sorted(quarantined)[:1]:
            req = urllib.request.Request(
                url + "/ask",
                data=json.dumps({"study_id": sid}).encode(),
                headers={"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(req, timeout=30)
                print("phase1: FAIL — quarantined ask answered 200",
                      file=sys.stderr)
                return 1
            except urllib.error.HTTPError as e:
                if e.code != 410:
                    print(f"phase1: FAIL — quarantined ask answered "
                          f"{e.code}, want 410", file=sys.stderr)
                    return 1
            tl = json.loads(_get(url, f"/study/{sid}/timeline"))
            if not any(ev.get("event") == "quarantine"
                       for ev in tl.get("events", [])):
                print("phase1: FAIL — no quarantine timeline event",
                      file=sys.stderr)
                return 1
        # healthy studies: zero lost acknowledged tells + bitwise
        healthy = [i for i in range(N_STUDIES)
                   if study_ids.get(i) and study_ids[i] not in quarantined]
        if not healthy:
            print("phase1: FAIL — every study quarantined; lower "
                  "CORRUPT_P", file=sys.stderr)
            return 1
        bad = 0
        from hyperopt_tpu.service import ServiceClient

        for i in healthy:
            s = by_sid[study_ids[i]]
            if s["n_pending"] != 0 or s["n_trials"] != BUDGET:
                print(f"phase1: FAIL — healthy study {i} lost state: "
                      f"{s['n_trials']} trials, {s['n_pending']} "
                      "pending", file=sys.stderr)
                return 1
            client = ServiceClient(url, key=100 + i, retry=20,
                                   timeout=60)
            cont = []
            for _ in range(EXTRA):
                t = client.ask(study_ids[i])[0]
                client.tell(study_ids[i], t["tid"],
                            _loss(t["params"], _offset(i)))
                cont.append((t["tid"], repr(t["params"]["x"])))
            if sequences[i] + cont != ref[i]:
                bad += 1
                print(f"phase1: healthy study {i} DIVERGED:\n"
                      f"  got  {sequences[i] + cont}\n"
                      f"  want {ref[i]}", file=sys.stderr)
        if bad:
            print(f"phase1: FAIL — {bad}/{len(healthy)} healthy "
                  "studies diverged", file=sys.stderr)
            return 1
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    # -- scrub --repair produces a store that boots clean --------------
    rc = subprocess.run(
        [sys.executable, "-m", "hyperopt_tpu.service.scrub", store,
         "--repair"],
        cwd=_REPO, env=_env(), capture_output=True, text=True).returncode
    if rc != 0:
        print(f"phase1: FAIL — scrub --repair exited {rc}",
              file=sys.stderr)
        return 1
    post = scrub_mod.scan_store(store)
    if not post["clean"]:
        print(f"phase1: FAIL — post-repair scan still faulty: "
              f"{post['faults']}", file=sys.stderr)
        return 1
    proc, url = _launch(["--port", "0", "--store", store], _env())
    if url is None:
        print("phase1: FAIL — repaired store never booted",
              file=sys.stderr)
        return 1
    try:
        table = json.loads(_get(url, "/studies"))
        still_q = [s for s in table["studies"]
                   if s.get("state") == "quarantined"]
        if found >= 1 and not still_q:
            print("phase1: FAIL — repair forgot the quarantine "
                  "markers", file=sys.stderr)
            return 1
    finally:
        proc.kill()
        proc.wait()
    print(f"phase1: PASS — {injected} injections, {len(quarantined)} "
          f"studies quarantined (410), {len(healthy)} healthy studies "
          f"bitwise with zero lost tells, repair boots clean")
    return 0


def phase2_enospc(store):
    from hyperopt_tpu.service import ServiceClient

    print("store_chaos_smoke: phase 2 — injected ENOSPC: 507 + "
          "Retry-After shed, automatic recovery, clients finish")
    proc, url = _launch(
        ["--port", "0", "--store", store],
        _env(chaos="31:enospc@wal:0.25"))
    if url is None:
        print("phase2: FAIL — server never announced", file=sys.stderr)
        return 1
    try:
        spec = {"x": {"dist": "uniform", "args": [-5, 5]}}
        n_clients, budget = 6, 6
        done = [0]
        retries = [0]
        errors = []
        lock = threading.Lock()

        def drive(i):
            client = ServiceClient(url, key=i, retry=40, timeout=60)
            try:
                sid = client.create_study(space=spec, seed=9000 + i,
                                          n_startup_jobs=2)
                for _ in range(budget):
                    t = client.ask(sid)[0]
                    client.tell(sid, t["tid"], _loss(t["params"], 0.0))
                with lock:
                    done[0] += 1
                    retries[0] += client.retries
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(f"client {i}: {type(e).__name__}: {e}")

        threads = [threading.Thread(target=drive, args=(i,))
                   for i in range(n_clients)]
        for t in threads:
            t.start()

        # raw-probe for a 507 while the clients hammer: the typed shed
        # must carry Retry-After on the wire
        saw_507 = False
        retry_after_ok = False
        probe_deadline = time.monotonic() + 60
        while time.monotonic() < probe_deadline and not saw_507:
            req = urllib.request.Request(
                url + "/ask",
                data=json.dumps({"study_id": "study-nonexistent"}
                                ).encode(),
                headers={"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(req, timeout=30)
            except urllib.error.HTTPError as e:
                if e.code == 507:
                    saw_507 = True
                    retry_after_ok = bool(e.headers.get("Retry-After"))
                # 404 = not latched right now: keep probing
            except Exception:  # noqa: BLE001
                pass
            time.sleep(0.05)
        for t in threads:
            t.join()
        if errors:
            print("phase2: FAIL — client errors (recovery broken?):",
                  file=sys.stderr)
            for e in errors[:10]:
                print("  " + e, file=sys.stderr)
            return 1
        metrics = _get(url, "/metrics")
        typed = (_metric(metrics, "hyperopt_tpu_service_shed_store_full_total")
                 + _metric(metrics, "hyperopt_tpu_chaos_enospc_wal_total"))
        if typed < 1:
            print("phase2: FAIL — no store-full shed/fault recorded",
                  file=sys.stderr)
            return 1
        if not saw_507:
            print("phase2: WARN — probe never caught an armed latch "
                  "(clients absorbed every window); typed metrics "
                  f"prove the path fired ({typed:.0f})")
        elif not retry_after_ok:
            print("phase2: FAIL — 507 without Retry-After",
                  file=sys.stderr)
            return 1
        if proc.poll() is not None:
            print("phase2: FAIL — server died under ENOSPC",
                  file=sys.stderr)
            return 1
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
        if rc != 0:
            print(f"phase2: FAIL — drain exited {rc}", file=sys.stderr)
            return 1
        print(f"phase2: PASS — {done[0]}/{n_clients} clients finished "
              f"through the full-disk windows ({retries[0]} backoffs, "
              f"507-with-Retry-After seen={saw_507})")
        return 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def main():
    import tempfile

    with tempfile.TemporaryDirectory() as store1:
        rc = phase1_corruption(store1)
        if rc:
            return rc
    with tempfile.TemporaryDirectory() as store2:
        rc = phase2_enospc(store2)
        if rc:
            return rc
    print("store_chaos_smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
