"""Rolling restart of a replicated serving fleet with zero lost tells.

For each replica IN TURN:

1. **SIGTERM it** — the server drains: every held study-shard hands off
   (in-flight waves finish, the shard's epoch WAL compacts to one
   snapshot per live study, the ownership entry clears, the lease
   releases) and the process exits 0.
2. **Wait for coverage AND blackbox-green** — poll the REMAINING
   replicas' ``GET /healthz`` until their held-shard tables jointly
   cover the whole keyspace again (survivors' stewards adopt the
   released shards by WAL replay; clients meanwhile ride 307/503 +
   Retry-After, never a hard failure) and, on every survivor that runs
   the blackbox prober (ISSUE 18), until its ``probe`` verdict is green
   — a restart must not march on while the remaining fleet is serving
   wrong or stale proposals that lease coverage alone cannot see.
   Replicas with the prober disarmed do not veto (you cannot gate on a
   signal nobody measures).
3. **Relaunch** — run the replica's launch command again and wait for
   the new process's ``/healthz`` to answer ``ok`` (its steward will be
   volunteered shards back by the rebalance).

Usage (one box; pids + healthz URLs + the relaunch command)::

    python scripts/fleet_restart.py \
        --replica 12345=http://127.0.0.1:9101 \
        --replica 12346=http://127.0.0.1:9102 \
        --relaunch 'python -m hyperopt_tpu.service.server --fleet \
                    --store /srv/hpo --port {port}'

``scripts/fleet_smoke.py`` drives :func:`restart_one` /
:func:`wait_coverage` in-process with live client traffic running — the
zero-lost-tells + bitwise-convergence assertions live there.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

__all__ = ["fetch_healthz", "fleet_coverage", "wait_coverage",
           "blackbox_green", "wait_blackbox_green", "wait_exit",
           "restart_one", "main"]


def fetch_healthz(url, timeout=3.0):
    """``GET <url>/healthz`` → dict, or None (a dead replica is a
    normal sight mid-restart, never an exception)."""
    try:
        with urllib.request.urlopen(url.rstrip("/") + "/healthz",
                                    timeout=timeout) as r:
            return json.loads(r.read().decode())
    except Exception:  # noqa: BLE001
        return None


def fleet_coverage(urls):
    """``(held shards union, n_shards)`` across the live replicas at
    ``urls`` (n_shards is None until any replica answers)."""
    held = set()
    n_shards = None
    for url in urls:
        h = fetch_healthz(url)
        if not h:
            continue
        held.update(int(s) for s in h.get("shards_held") or [])
        if h.get("n_shards"):
            n_shards = int(h["n_shards"])
    return held, n_shards


def wait_coverage(urls, timeout=60.0, poll=0.2):
    """Block until the replicas at ``urls`` jointly hold EVERY shard
    (the handed-off/reclaimed keyspace is fully re-adopted).  Returns
    True on success, False on timeout."""
    deadline = time.monotonic() + float(timeout)
    while time.monotonic() < deadline:
        held, n_shards = fleet_coverage(urls)
        if n_shards is not None and len(held) >= n_shards:
            return True
        time.sleep(poll)
    return False


def blackbox_green(urls):
    """True when every replica at ``urls`` answers healthz AND every
    one that reports blackbox-probe fields (prober armed) is green —
    newest canary verdict ``ok`` and fresh.  A replica with the prober
    disarmed (no ``probe`` section) never vetoes: the gate tightens
    when the signal exists, it does not manufacture one."""
    for url in urls:
        h = fetch_healthz(url)
        if not h:
            return False
        probe = h.get("probe")
        if probe is not None and not probe.get("green"):
            return False
    return True


def wait_blackbox_green(urls, timeout=60.0, poll=0.2):
    """Block until :func:`blackbox_green` holds for ``urls``.  Returns
    True on success, False on timeout."""
    deadline = time.monotonic() + float(timeout)
    while time.monotonic() < deadline:
        if blackbox_green(urls):
            return True
        time.sleep(poll)
    return False


def wait_exit(pid, timeout=60.0, poll=0.1):
    """Wait for ``pid`` to exit.  Uses ``waitpid`` for our own children
    (returns the exit code) and signal-0 polling for foreign pids
    (returns None once gone).  False on timeout."""
    deadline = time.monotonic() + float(timeout)
    while time.monotonic() < deadline:
        try:
            got, status = os.waitpid(pid, os.WNOHANG)
            if got == pid:
                return os.waitstatus_to_exitcode(status)
        except ChildProcessError:
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                return None  # foreign pid, gone
        time.sleep(poll)
    return False


def restart_one(pid, url, other_urls, relaunch=None, timeout=120.0):
    """One rolling-restart step: SIGTERM ``pid``, wait for its drain
    exit, wait for the survivors at ``other_urls`` to cover the
    keyspace, then run ``relaunch`` (a list/str command) and wait for
    the reborn replica's healthz.  Returns the new Popen (or None
    without ``relaunch``); raises on a step that never converged."""
    os.kill(pid, signal.SIGTERM)
    rc = wait_exit(pid, timeout=timeout)
    if rc is False:
        raise RuntimeError(f"replica pid {pid} ignored SIGTERM (drain "
                           "hung)")
    if rc not in (None, 0):
        raise RuntimeError(f"replica pid {pid} drained with exit {rc}, "
                           "want 0")
    if other_urls and not wait_coverage(other_urls, timeout=timeout):
        raise RuntimeError("survivors never re-adopted the drained "
                           f"shards (urls: {other_urls})")
    if other_urls and not wait_blackbox_green(other_urls,
                                              timeout=timeout):
        raise RuntimeError(
            "survivors are not blackbox-green (canary probe verdict "
            "not ok/fresh) — refusing to take down the next replica "
            f"while the fleet serves suspect proposals (urls: "
            f"{other_urls})")
    if relaunch is None:
        return None
    cmd = relaunch if isinstance(relaunch, (list, tuple)) else [
        "sh", "-c", relaunch]
    proc = subprocess.Popen(list(cmd))
    deadline = time.monotonic() + float(timeout)
    while time.monotonic() < deadline:
        h = fetch_healthz(url)
        if h and h.get("ok") and (h.get("probe") is None
                                  or h["probe"].get("green")):
            # the reborn replica must be blackbox-green too (when its
            # prober is armed) before the next step proceeds
            return proc
        if proc.poll() is not None:
            raise RuntimeError(
                f"relaunched replica exited {proc.returncode} before "
                "its healthz answered")
        time.sleep(0.2)
    raise RuntimeError(f"relaunched replica at {url} never answered "
                       "healthz")


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python scripts/fleet_restart.py",
        description="Rolling restart of serving-fleet replicas with "
                    "handoff-verified zero-lost-tells ordering.")
    p.add_argument("--replica", action="append", required=True,
                   metavar="PID=URL",
                   help="a replica's pid and healthz base URL "
                        "(repeatable; restarted in the given order)")
    p.add_argument("--relaunch", default=None,
                   help="shell command to relaunch a replica "
                        "({port} substituted from its URL); omit to "
                        "only drain-and-redistribute")
    p.add_argument("--timeout", type=float, default=120.0,
                   help="per-step convergence timeout (default 120s)")
    args = p.parse_args(argv)

    replicas = []
    for spec in args.replica:
        pid_s, _, url = spec.partition("=")
        if not url:
            p.error(f"--replica wants PID=URL, got {spec!r}")
        replicas.append((int(pid_s), url.rstrip("/")))

    for i, (pid, url) in enumerate(replicas):
        others = [u for j, (_, u) in enumerate(replicas) if j != i]
        relaunch = None
        if args.relaunch:
            port = url.rsplit(":", 1)[-1]
            relaunch = args.relaunch.format(port=port)
        print(f"fleet_restart: [{i + 1}/{len(replicas)}] draining pid "
              f"{pid} ({url})", flush=True)
        restart_one(pid, url, others, relaunch=relaunch,
                    timeout=args.timeout)
        print(f"fleet_restart: [{i + 1}/{len(replicas)}] done", flush=True)
    print("fleet_restart: rolling restart complete")
    return 0


if __name__ == "__main__":
    sys.exit(main())
