"""COMPILE_GATE end-to-end smoke: the cold-start compile plane on a REAL
subprocess server, cold store, novel spaces, concurrent load, restart.

What it pins (the cold-start contract no unit test can):

* a plane-armed server (``--compile-plane on``) serving spaces it has
  NEVER compiled answers every ask at the warming rand floor — **no ask
  ever blocks on an XLA compile** (hard wall-clock bar per ask, while
  ``/metrics`` proves real compiles happened in the background);
* warming is honest and converges: early asks carry ``warming: true``,
  and once the background queue drains the same studies' asks come back
  un-flagged (promoted to TPE);
* the census kernel bank round-trips a RESTART: a second server on the
  same store root (same ``HYPEROPT_TPU_COMPILE_CACHE``) pre-warms the
  census keys before its listener opens, so the same spaces' first
  TPE-eligible asks are served on-device — zero warming flags — and
  ``/metrics`` shows ``service.compile.bank`` keys;
* both servers exit 0 on SIGTERM.

Opt in via ``COMPILE_GATE=1 ./run_tests.sh``.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

N_SPACES = 6
ASKS_PER_STUDY = 4
N_WORKERS = 6
#: per-ask wall bar proving no ask waited for a compile: the cold
#: phase's compile BACKLOG is ~N_SPACES × seconds of XLA (≈10s serial
#: on the 2-core box) — an ask that actually waited for its program
#: would pay that.  The rand floor itself is milliseconds, but while
#: the background thread compiles it steals most of both cores (XLA
#: releases the GIL, the Python handler still fights for CPU), so
#: measured floor asks spike to ~2s under full queue pressure; 5s
#: cleanly separates "contended but never blocked" from "blocked".
MAX_ASK_SEC = 5.0


def _get(url, path, timeout=60):
    with urllib.request.urlopen(url + path, timeout=timeout) as r:
        return r.status, r.read()


def _metric(text, name):
    for line in text.splitlines():
        if line.startswith(name + " ") or line.startswith(name + "{"):
            try:
                return float(line.rsplit(None, 1)[1])
            except ValueError:
                pass
    return None


def _spawn(env, store):
    proc = subprocess.Popen(
        [sys.executable, "-m", "hyperopt_tpu.service.server",
         "--port", "0", "--announce", "--store", store,
         "--compile-plane", "on"],
        cwd=_REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    url = None
    deadline = time.monotonic() + 180
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.startswith("SERVICE_URL "):
            url = line.split(None, 1)[1].strip()
            break
        if proc.poll() is not None:
            break
    return proc, url


def _stop(proc):
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=60)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=10)
        return None
    return proc.returncode


def _wire_spaces():
    # distinct-but-similar signatures: every (low, high) pair is its own
    # cohort key, so a cold server compiles one program per space
    out = []
    for i in range(N_SPACES):
        lo, hi = -4.0 - 0.01 * i, 3.0 + 0.01 * i
        out.append({"x": {"dist": "uniform", "args": [lo, hi]},
                    "lr": {"dist": "loguniform", "args": [lo, 0.0]}})
    return out


def _drive(url, phase, errors, stats, lock):
    from hyperopt_tpu.service import ServiceClient

    spaces = _wire_spaces()
    work = list(range(N_SPACES))

    def one():
        client = ServiceClient(url, retry=8, key=threading.get_ident())
        while True:
            with lock:
                if not work:
                    return
                i = work.pop()
            try:
                sid = client.create_study(space=spaces[i],
                                          seed=7000 + i,
                                          n_startup_jobs=1)
                for j in range(ASKS_PER_STUDY):
                    t0 = time.perf_counter()
                    trials = client.ask(sid)
                    dt = time.perf_counter() - t0
                    warming = any(t.get("warming") for t in trials)
                    with lock:
                        stats["ask_sec"].append(dt)
                        if warming:
                            stats["warming"] += 1
                        # j==0 is the startup rand draw (never warming);
                        # j==1 is the first TPE-eligible ask — the
                        # restart phase pins it cold-free
                        if j == 1:
                            stats["first_tpe_warming"] += int(warming)
                    for t in trials:
                        client.tell(sid, t["tid"],
                                    (t["params"]["x"] - 0.5) ** 2)
                with lock:
                    stats["done"].append(sid)
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(f"{phase} study {i}: "
                                  f"{type(e).__name__}: {e}")

    threads = [threading.Thread(target=one) for _ in range(N_WORKERS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def main():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    with tempfile.TemporaryDirectory() as tmp:
        store = os.path.join(tmp, "store")
        os.makedirs(store)
        # the persistent XLA cache is the bank's cross-restart fast path
        env["HYPEROPT_TPU_COMPILE_CACHE"] = os.path.join(tmp, "xla_cache")

        # ---- phase A: cold server, novel spaces, concurrent load ------
        proc, url = _spawn(env, store)
        if url is None:
            print("coldstart_smoke: FAIL — server never announced",
                  file=sys.stderr)
            print((proc.stderr.read() or "")[-2000:], file=sys.stderr)
            return 1
        print(f"coldstart_smoke: cold server up at {url} (pid {proc.pid})")
        errors = []
        stats = {"ask_sec": [], "warming": 0, "first_tpe_warming": 0,
                 "done": []}
        lock = threading.Lock()
        _drive(url, "cold", errors, stats, lock)
        if errors:
            print("coldstart_smoke: FAIL — client errors:",
                  file=sys.stderr)
            for e in errors[:10]:
                print("  " + e, file=sys.stderr)
            return 1
        worst = max(stats["ask_sec"])
        print(f"coldstart_smoke: cold phase — {len(stats['done'])} studies"
              f" x {ASKS_PER_STUDY} asks, worst ask {worst * 1e3:.0f}ms, "
              f"{stats['warming']} warming-served asks")
        if worst > MAX_ASK_SEC:
            print(f"coldstart_smoke: FAIL — an ask took {worst:.2f}s "
                  f"(> {MAX_ASK_SEC}s): it blocked on a compile",
                  file=sys.stderr)
            return 1
        if stats["warming"] == 0:
            print("coldstart_smoke: FAIL — no ask was ever "
                  "warming-flagged on a COLD server (plane not armed?)",
                  file=sys.stderr)
            return 1
        # the background compiles must be REAL (queue drains to served
        # TPE asks): poll /metrics until compiled_total covers the keys
        # and nothing is outstanding (the queue_depth gauge counts
        # in-flight work too — a popped-but-still-compiling job must
        # not read as "drained")
        deadline = time.monotonic() + 300
        compiled = 0
        while time.monotonic() < deadline:
            text = _get(url, "/metrics")[1].decode()
            compiled = _metric(
                text,
                "hyperopt_tpu_service_compile_compiled_total_total") or 0
            enq = _metric(
                text,
                "hyperopt_tpu_service_compile_enqueued_total") or 0
            errs = _metric(
                text, "hyperopt_tpu_service_compile_errors_total") or 0
            if (compiled + errs >= enq and enq >= 1 and (_metric(
                    text,
                    "hyperopt_tpu_service_compile_queue_depth") or 0)
                    == 0):
                break
            time.sleep(0.5)
        if compiled < 1:
            print("coldstart_smoke: FAIL — background thread never "
                  "compiled anything", file=sys.stderr)
            return 1
        if errs:
            print(f"coldstart_smoke: FAIL — {errs:.0f} background "
                  "compile jobs errored (check server stderr)",
                  file=sys.stderr)
            print((proc.stderr.read() or "")[-2000:], file=sys.stderr)
            return 1
        print(f"coldstart_smoke: background compiled {compiled:.0f}/"
              f"{enq:.0f} programs; queue drained")
        # post-drain asks must be promoted (no warming flag)
        from hyperopt_tpu.service import ServiceClient

        client = ServiceClient(url, retry=8, key=1)
        sid = stats["done"][0]
        trials = client.ask(sid)
        if any(t.get("warming") for t in trials):
            print("coldstart_smoke: FAIL — still warming after the "
                  "queue drained (promotion broken)", file=sys.stderr)
            return 1
        client.tell(sid, trials[0]["tid"], 0.1)
        rc = _stop(proc)
        if rc != 0:
            print(f"coldstart_smoke: FAIL — cold server exit {rc}",
                  file=sys.stderr)
            return 1

        # ---- phase B: restart — the census bank pre-warms ------------
        census = os.path.join(store, "compile_census.jsonl")
        if not os.path.exists(census):
            print("coldstart_smoke: FAIL — no census written",
                  file=sys.stderr)
            return 1
        proc, url = _spawn(env, store)
        if url is None:
            print("coldstart_smoke: FAIL — restarted server never "
                  "announced", file=sys.stderr)
            print((proc.stderr.read() or "")[-2000:], file=sys.stderr)
            return 1
        print(f"coldstart_smoke: restarted server up at {url}")
        errors = []
        stats2 = {"ask_sec": [], "warming": 0, "first_tpe_warming": 0,
                  "done": []}
        _drive(url, "warm", errors, stats2, lock)
        if errors:
            print("coldstart_smoke: FAIL — restart client errors:",
                  file=sys.stderr)
            for e in errors[:10]:
                print("  " + e, file=sys.stderr)
            return 1
        text = _get(url, "/metrics")[1].decode()
        bank_keys = _metric(
            text, "hyperopt_tpu_service_compile_bank_keys") or 0
        if bank_keys < 1:
            print("coldstart_smoke: FAIL — restarted server warmed no "
                  "bank keys from the census", file=sys.stderr)
            return 1
        if stats2["first_tpe_warming"]:
            print(f"coldstart_smoke: FAIL — {stats2['first_tpe_warming']}"
                  " first TPE asks were warming-served AFTER the bank "
                  "warm (census keys did not match live cohort keys)",
                  file=sys.stderr)
            return 1
        worst2 = max(stats2["ask_sec"])
        print(f"coldstart_smoke: restart phase — bank keys "
              f"{bank_keys:.0f}, zero warming on first TPE asks, worst "
              f"ask {worst2 * 1e3:.0f}ms")
        rc = _stop(proc)
        if rc != 0:
            print(f"coldstart_smoke: FAIL — restarted server exit {rc}",
                  file=sys.stderr)
            return 1
    print("coldstart_smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
