"""FLEET_GATE end-to-end smoke: a REAL 3-replica serving fleet over one
shared store root — one replica SIGKILLed mid-wave under concurrent
ServiceClient drivers, then a scripted rolling restart of every replica
— with every study's final trial history bit-identical to an undisturbed
single-server reference, zero lost and zero duplicated tells, and every
ask served within a bounded retry window.

What it pins (the replication contract no unit test can):

* phase 1 — **SIGKILL one of three, fleet converges bitwise**: replica
  r1 runs a deterministic chaos schedule (``kill@tick:8`` — SIGKILL
  inside a cohort-tick dispatch: mid-wave, post-draw, pre-journal, the
  window the WAL ordering argument covers).  Nine concurrent clients
  (three homed on the doomed replica) ride through the death on the
  client's 307/503/connection-error retry ladder while the survivors'
  stewards reclaim the dead replica's shard leases (TTL expiry,
  rename-first) and adopt its studies by epoch-WAL replay.  The dead
  replica is NEVER restarted — the fleet absorbs it.  Every study's
  full (tid, params) sequence must equal the undisturbed in-process
  single-scheduler reference at the same seeds, every study must end
  with exactly its budget of trials and zero pending (no tell lost,
  none double-applied — a 409 on a retried tell counts as the dedupe
  working), and the measured ask p99 must stay under the retry-window
  bound.

* phase 2 — **rolling restart, zero lost tells**: all three replicas
  are restarted IN TURN through ``scripts/fleet_restart.py``'s
  SIGTERM → drain-exit-0 → survivors-cover-keyspace → relaunch →
  healthz-ok sequence, with client traffic running throughout.  Same
  bitwise + zero-lost/zero-duplicate assertions at the end.

Opt in via ``FLEET_GATE=1 ./run_tests.sh``.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "scripts"))

from fleet_restart import fetch_healthz, wait_coverage, wait_exit  # noqa: E402

N_SHARDS = 6
LEASE_TTL = 2.0


def _env(chaos=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("HYPEROPT_TPU_CHAOS", None)
    if chaos:
        env["HYPEROPT_TPU_CHAOS"] = chaos
    return env


def _launch(store, rid, port="0", chaos=None):
    proc = subprocess.Popen(
        [sys.executable, "-m", "hyperopt_tpu.service.server",
         "--announce", "--port", str(port), "--store", store,
         "--fleet", "--fleet-shards", str(N_SHARDS),
         "--lease-ttl", str(LEASE_TTL), "--replica-id", rid],
        cwd=_REPO, env=_env(chaos=chaos), stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True)
    deadline = time.monotonic() + 180
    url = None
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.startswith("SERVICE_URL "):
            url = line.split(None, 1)[1].strip()
            break
        if proc.poll() is not None:
            break
    return proc, url


def _loss(params, offset):
    return float((params["x"] - offset) ** 2)


def _offset(i, n):
    return -4.0 + 8.0 * i / max(1, n - 1)


def _reference_sequences(n_studies, budget, n_startup, seed0):
    """Undisturbed in-process reference: same seeds, same serial
    per-study ask→tell order, single scheduler, no store, no fleet."""
    from hyperopt_tpu import hp
    from hyperopt_tpu.service import StudyScheduler

    space = {"x": hp.uniform("x", -5, 5)}
    ref = {}
    for i in range(n_studies):
        sched = StudyScheduler(wal=False, max_studies=64)
        sid = sched.create_study(space, seed=seed0 + i,
                                 n_startup_jobs=n_startup)
        seq = []
        for _ in range(budget):
            a = sched.ask(sid)[0]
            sched.tell(sid, a["tid"], _loss(a["params"], _offset(i, n_studies)))
            seq.append((a["tid"], repr(a["params"]["x"])))
        ref[i] = seq
    return ref


class _Driver(threading.Thread):
    """One study's client: create → budget x (ask → tell), riding every
    fleet event (307, 503, connection error, Retry-After) on the
    client's deterministic retry ladder.  Records the (tid, params)
    sequence, per-ask wall latencies and duplicate-tell count."""

    def __init__(self, i, n_studies, urls, budget, n_startup, seed0):
        super().__init__()
        self.i = i
        self.n = n_studies
        self.urls = urls
        self.budget = budget
        self.n_startup = n_startup
        self.seed0 = seed0
        self.seq = None
        self.study_id = None
        self.ask_sec = []
        self.duplicates = 0
        self.error = None

    def run(self):
        from hyperopt_tpu.retry import RetryPolicy
        from hyperopt_tpu.service import ServiceClient

        # home each driver on a different replica; generous budget so a
        # client rides TTL expiry + WAL replay + XLA compile on adopt
        seeds = self.urls[self.i % len(self.urls):] \
            + self.urls[:self.i % len(self.urls)]
        client = ServiceClient(
            seeds, key=self.i, timeout=60,
            retry=RetryPolicy(max_retries=80, base_delay=0.2,
                              max_delay=2.0))
        spec = {"x": {"dist": "uniform", "args": [-5, 5]}}
        try:
            sid = client.create_study(
                space=spec, seed=self.seed0 + self.i,
                n_startup_jobs=self.n_startup, max_trials=self.budget)
            seq = []
            for _ in range(self.budget):
                t0 = time.perf_counter()
                t = client.ask(sid)[0]
                self.ask_sec.append(time.perf_counter() - t0)
                r = client.tell(sid, t["tid"],
                                _loss(t["params"], _offset(self.i, self.n)))
                if r.get("duplicate"):
                    self.duplicates += 1
                seq.append((t["tid"], repr(t["params"]["x"])))
            self.seq = seq
            self.study_id = sid
        except Exception as e:  # noqa: BLE001
            self.error = f"study {self.i}: {type(e).__name__}: {e}"


def _merged_study_table(urls):
    """Union of every live replica's /studies table (a study appears on
    its current owner)."""
    out = {}
    for url in urls:
        try:
            with urllib.request.urlopen(url + "/studies", timeout=30) as r:
                table = json.loads(r.read())
        except Exception:  # noqa: BLE001 - dead replicas are expected
            continue
        for s in table.get("studies", []):
            out[s["study_id"]] = s
    return out


def _store_counts(store, study_id):
    """``(n_done, n_total)`` straight from the study's on-disk store —
    the durable record a DONE study keeps after WAL compaction forgets
    its registry entry (the documented ISSUE-10 bound)."""
    import pickle

    done = total = 0
    root = os.path.join(store, study_id)
    for state in ("new", "running", "done", "error", "cancel"):
        d = os.path.join(root, state)
        if not os.path.isdir(d):
            continue
        for fname in os.listdir(d):
            if not fname.endswith(".pkl"):
                continue
            total += 1
            if state == "done":
                with open(os.path.join(d, fname), "rb") as f:
                    doc = pickle.load(f)
                if doc.get("result", {}).get("status") is not None:
                    done += 1
    return done, total


def _check_results(drivers, ref, live_urls, budget, label, store):
    """The acceptance bars shared by both phases: no client errors,
    bitwise vs reference, zero pending / zero lost / zero duplicated
    tells (live table for registered studies, the durable store for
    DONE studies compaction already forgot), bounded p99."""
    errors = [d.error for d in drivers if d.error]
    if errors:
        print(f"{label}: FAIL — client errors:", file=sys.stderr)
        for e in errors[:10]:
            print("  " + e, file=sys.stderr)
        return False
    bad = 0
    for d in drivers:
        if d.seq != ref[d.i]:
            bad += 1
            print(f"{label}: study {d.i} DIVERGED:\n  got  {d.seq}\n"
                  f"  want {ref[d.i]}", file=sys.stderr)
    if bad:
        print(f"{label}: FAIL — {bad}/{len(drivers)} studies diverged "
              "from the undisturbed reference", file=sys.stderr)
        return False
    table = _merged_study_table(live_urls)
    lost = []
    for d in drivers:
        s = table.get(d.study_id)
        if s is not None:
            if s["n_trials"] != budget or s["n_pending"]:
                lost.append((d.i, s["n_trials"], s["n_pending"]))
        else:
            # completed studies drop out of the registry at the next
            # migration's compaction BY DESIGN; their trials are on disk
            done, total = _store_counts(store, d.study_id)
            if done != budget or total != budget:
                lost.append((d.i, total, total - done))
    if lost:
        print(f"{label}: FAIL — {len(lost)} studies with lost or "
              f"duplicated tells: {lost}", file=sys.stderr)
        return False
    lat = sorted(t for d in drivers for t in d.ask_sec)
    p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))]
    dups = sum(d.duplicates for d in drivers)
    # bounded: TTL expiry + steward poll + WAL replay + compile, well
    # under the client's ~160s worst-case retry window
    if p99 > 60.0:
        print(f"{label}: FAIL — ask p99 {p99:.1f}s unbounded",
              file=sys.stderr)
        return False
    print(f"{label}: ask p50 {lat[len(lat) // 2] * 1e3:.0f}ms "
          f"p99 {p99 * 1e3:.0f}ms over {len(lat)} asks; "
          f"{dups} duplicate-tell dedupes")
    return True


def phase1_sigkill():
    print("fleet_smoke: phase 1 — SIGKILL one replica of three under "
          "concurrent clients; fleet converges bitwise")
    n_studies, budget, n_startup, seed0 = 9, 12, 3, 3000
    ref = _reference_sequences(n_studies, budget, n_startup, seed0)

    with tempfile.TemporaryDirectory() as store:
        procs, urls = [], []
        for i, chaos in enumerate([None, "11:kill@tick:8", None]):
            proc, url = _launch(store, f"r{i}", chaos=chaos)
            if url is None:
                print(f"phase1: FAIL — replica r{i} never announced",
                      file=sys.stderr)
                return 1
            procs.append(proc)
            urls.append(url)
        try:
            if not wait_coverage(urls, timeout=60):
                print("phase1: FAIL — fleet never covered the keyspace",
                      file=sys.stderr)
                return 1
            drivers = [_Driver(i, n_studies, urls, budget, n_startup,
                               seed0) for i in range(n_studies)]
            for d in drivers:
                d.start()
            # supervise: the armed replica dies mid-wave; survivors
            # absorb it — NO restart
            deaths = 0
            while any(d.is_alive() for d in drivers):
                for i, proc in enumerate(procs):
                    if proc is not None and proc.poll() is not None:
                        deaths += 1
                        print(f"phase1: replica r{i} died "
                              f"(rc {proc.returncode}); survivors "
                              "reclaim its shards", flush=True)
                        procs[i] = None
                time.sleep(0.1)
            for d in drivers:
                d.join()
            if deaths != 1:
                print(f"phase1: FAIL — expected exactly 1 chaos death, "
                      f"saw {deaths}", file=sys.stderr)
                return 1
            live = [u for u, p in zip(urls, procs) if p is not None]
            if not wait_coverage(live, timeout=60):
                print("phase1: FAIL — survivors never re-covered the "
                      "keyspace", file=sys.stderr)
                return 1
            if not _check_results(drivers, ref, live, budget, "phase1",
                                  store):
                return 1
            # the survivors' healthz must show the adoption traffic
            adopts = sum((fetch_healthz(u) or {}).get("adoptions", 0)
                         for u in live)
            print(f"phase1: PASS — {n_studies} studies x {budget} trials "
                  f"bitwise-identical through 1 SIGKILL "
                  f"({adopts} shard adoptions across survivors)")
            return 0
        finally:
            for proc in procs:
                if proc is not None and proc.poll() is None:
                    proc.kill()
                    proc.wait()


def phase2_rolling_restart():
    print("fleet_smoke: phase 2 — scripted rolling restart of all "
          "replicas under traffic; zero lost tells")
    n_studies, budget, n_startup, seed0 = 6, 10, 3, 7000
    ref = _reference_sequences(n_studies, budget, n_startup, seed0)

    with tempfile.TemporaryDirectory() as store:
        procs, urls = [], []
        for i in range(3):
            proc, url = _launch(store, f"s{i}")
            if url is None:
                print(f"phase2: FAIL — replica s{i} never announced",
                      file=sys.stderr)
                return 1
            procs.append(proc)
            urls.append(url)
        try:
            if not wait_coverage(urls, timeout=60):
                print("phase2: FAIL — fleet never covered the keyspace",
                      file=sys.stderr)
                return 1
            drivers = [_Driver(i, n_studies, urls, budget, n_startup,
                               seed0) for i in range(n_studies)]
            for d in drivers:
                d.start()
            time.sleep(1.0)  # let traffic build before the first drain
            # the rolling restart: SIGTERM → drain exit 0 → survivors
            # cover the keyspace → relaunch on the same port → healthz
            # ok (scripts/fleet_restart.py's sequence, driven in-process
            # so the relaunch can reuse _launch's announce handshake)
            for i in range(3):
                others = [u for j, u in enumerate(urls) if j != i]
                procs[i].send_signal(signal.SIGTERM)
                rc = wait_exit(procs[i].pid, timeout=90)
                if rc not in (0, None):
                    print(f"phase2: FAIL — replica s{i} drained with "
                          f"exit {rc}, want 0", file=sys.stderr)
                    return 1
                if not wait_coverage(others, timeout=60):
                    print("phase2: FAIL — survivors never re-adopted "
                          f"s{i}'s shards", file=sys.stderr)
                    return 1
                port = urls[i].rsplit(":", 1)[1]
                proc, url = _launch(store, f"s{i}", port=port)
                if url is None:
                    print(f"phase2: FAIL — relaunched s{i} never "
                          "announced", file=sys.stderr)
                    return 1
                procs[i], urls[i] = proc, url
                h = fetch_healthz(url)
                if not (h and h.get("ok")):
                    print(f"phase2: FAIL — relaunched s{i} healthz not "
                          "ok", file=sys.stderr)
                    return 1
                print(f"phase2: restarted replica s{i} "
                      f"({i + 1}/3)", flush=True)
            for d in drivers:
                d.join()
            if not _check_results(drivers, ref, urls, budget, "phase2",
                                  store):
                return 1
            handoffs = sum((fetch_healthz(u) or {}).get("handoffs", 0)
                           for u in urls)
            print(f"phase2: PASS — {n_studies} studies x {budget} trials "
                  "bitwise-identical through a full rolling restart "
                  f"(≥{handoffs} live handoffs visible post-restart)")
            return 0
        finally:
            for proc in procs:
                if proc is not None and proc.poll() is None:
                    proc.kill()
                    proc.wait()


def main():
    for phase in (phase1_sigkill, phase2_rolling_restart):
        rc = phase()
        if rc:
            return rc
    print("fleet_smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
