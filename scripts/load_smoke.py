"""LOAD_GATE end-to-end smoke (ISSUE 17): the cost-attribution
observatory over a REAL 3-replica serving fleet on one shared store
root, with a deliberately skewed (~10:1) study placement.

What it pins (the fleet-wide aggregation contract no unit test can):

* phase 1 — **skew is visible on every surface**: ~10 studies homed on
  one hot shard vs one study on each other shard, all driven past
  startup so real device waves burn heat.  Then: ``GET /fleet/load``
  on EVERY replica returns the merged fleet heat table with the hot
  shard hottest and ``heat_skew`` well above balanced; the
  ``service.load.*`` gauge family (per-shard heat, busy fraction, the
  skew scalar) appears on ``/metrics`` and the scrape LINTS clean
  (``validate_scrape.validate_metrics_text``); ``/snapshot`` carries
  the load section; ``/studies`` rows carry the per-study cost column;
  a raw ``/ask`` answer carries the ``wave`` correlation field; and
  zero tells are lost (every study ends with exactly its budget told,
  none pending).

* phase 2 — **heat follows the shard through BOTH migration paths**:
  a third replica joins an overfull two-replica fleet and the
  volunteer handoff releases the HOTTEST held shard first (the
  ISSUE-17 ordering change — pre-PR the highest shard number went);
  the adopter's ``/healthz`` shows the shard arriving with its
  accumulated heat (graceful-handoff inheritance).  Then the current
  owner is SIGKILLed mid-serving: survivors reclaim the lease, replay
  the durable heat ledger, and the shard is STILL hot on its new
  owner — plus the driven study keeps accepting asks/tells across the
  kill with zero lost tells.

Opt in via ``LOAD_GATE=1 ./run_tests.sh``.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "scripts"))

from fleet_restart import wait_coverage  # noqa: E402

LEASE_TTL = 2.0


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("HYPEROPT_TPU_CHAOS", None)
    env.pop("HYPEROPT_TPU_LOAD", None)   # default ON is the pin
    return env


def _launch(store, rid, n_shards, port="0"):
    proc = subprocess.Popen(
        [sys.executable, "-m", "hyperopt_tpu.service.server",
         "--announce", "--port", str(port), "--store", store,
         "--fleet", "--fleet-shards", str(n_shards),
         "--lease-ttl", str(LEASE_TTL), "--replica-id", rid],
        cwd=_REPO, env=_env(), stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True)
    deadline = time.monotonic() + 180
    url = None
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.startswith("SERVICE_URL "):
            url = line.split(None, 1)[1].strip()
            break
        if proc.poll() is not None:
            break
    return proc, url


def _fetch(url, path, timeout=30):
    with urllib.request.urlopen(url + path, timeout=timeout) as r:
        body = r.read()
    if path == "/metrics":
        return body.decode()
    return json.loads(body)


def _client(urls, key=0):
    from hyperopt_tpu.retry import RetryPolicy
    from hyperopt_tpu.service import ServiceClient

    return ServiceClient(list(urls), key=key, timeout=60,
                         retry=RetryPolicy(max_retries=80, base_delay=0.2,
                                           max_delay=2.0))


def _drive(client, sid, n):
    for _ in range(n):
        t = client.ask(sid)[0]
        client.tell(sid, t["tid"], loss=float(t["params"]["x"] ** 2))


def _study_rows(urls):
    rows = {}
    for url in urls:
        try:
            table = _fetch(url, "/studies")
        except Exception:  # noqa: BLE001 - dead replicas are expected
            continue
        for s in table.get("studies", []):
            rows[s["study_id"]] = s
    return rows


def _held(url):
    return set((_fetch(url, "/healthz") or {}).get("shards_held", []))


SPEC = {"x": {"dist": "uniform", "args": [-5, 5]}}


def phase1_skew_surfaces():
    from hyperopt_tpu.service import shard_of

    n_shards = 6
    print("load_smoke: phase 1 — 3 replicas, ~10:1 skewed placement; "
          "skew visible and linting on every surface")
    from validate_scrape import validate_metrics_text

    with tempfile.TemporaryDirectory() as store:
        procs, urls = [], []
        try:
            for i in range(3):
                proc, url = _launch(store, f"r{i}", n_shards)
                if url is None:
                    print(f"phase1: FAIL — replica r{i} never announced",
                          file=sys.stderr)
                    return 1
                procs.append(proc)
                urls.append(url)
            if not wait_coverage(urls, timeout=60):
                print("phase1: FAIL — fleet never covered the keyspace",
                      file=sys.stderr)
                return 1
            client = _client(urls)
            # mint the skewed placement: ~10 studies on one hot shard,
            # one study on each of two cold shards (the ids hash to
            # shards, so keep minting until the census is met; every
            # extra mint is torn down by max_trials=0 asks never sent)
            hot = None
            hot_sids, cold_sids = [], {}
            for seed in range(200):
                sid = client.create_study(space=SPEC, seed=1000 + seed,
                                          n_startup_jobs=2, max_trials=8)
                shard = shard_of(sid, n_shards)
                if hot is None:
                    hot = shard
                if shard == hot and len(hot_sids) < 10:
                    hot_sids.append(sid)
                elif shard != hot and shard not in cold_sids:
                    cold_sids[shard] = sid
                if len(hot_sids) == 10 and len(cold_sids) >= 2:
                    break
            else:
                print("phase1: FAIL — could not mint the skewed census",
                      file=sys.stderr)
                return 1
            print(f"phase1: placement skew {len(hot_sids)}:1 — "
                  f"{len(hot_sids)} studies on shard {hot}, 1 on each of "
                  f"{sorted(cold_sids)}")
            # budget 4 with startup 2: the last two asks are REAL device
            # waves — the hot shard burns ~10x the cohort ticks
            for sid in hot_sids:
                _drive(client, sid, 4)
            for sid in cold_sids.values():
                _drive(client, sid, 4)
            time.sleep(2.5)               # > the 1s heat-roll cadence

            # every replica serves the merged fleet view
            for url in urls:
                fl = _fetch(url, "/fleet/load")
                if not fl.get("ok") or "fleet" not in fl:
                    print(f"phase1: FAIL — {url}/fleet/load missing the "
                          f"fleet section: {fl}", file=sys.stderr)
                    return 1
            fl = _fetch(urls[0], "/fleet/load")["fleet"]
            if not fl["shards"]:
                print("phase1: FAIL — no heat records in the fleet view",
                      file=sys.stderr)
                return 1
            hottest = max(fl["shards"], key=lambda k:
                          fl["shards"][k]["heat_ms"])
            if hottest != str(hot):
                print(f"phase1: FAIL — hottest shard {hottest}, want "
                      f"{hot}: {fl['shards']}", file=sys.stderr)
                return 1
            if fl["heat_skew"] < 2.0:
                print(f"phase1: FAIL — fleet heat_skew "
                      f"{fl['heat_skew']} does not reflect the ~10:1 "
                      "placement", file=sys.stderr)
                return 1
            if fl["corrupt"]:
                print(f"phase1: FAIL — {fl['corrupt']} corrupt ledger "
                      "records on a clean run", file=sys.stderr)
                return 1

            # the gauge family lints on the owner's scrape
            owner = next(u for u in urls if hot in _held(u))
            text = _fetch(owner, "/metrics")
            errors = validate_metrics_text(text)
            if errors:
                print("phase1: FAIL — /metrics lint errors:",
                      file=sys.stderr)
                for e in errors[:10]:
                    print("  " + e, file=sys.stderr)
                return 1
            for needle in ("service_load_heat_skew",
                           "service_load_busy_frac",
                           f"service_load_shard_{hot}_heat_ms"):
                if needle not in text:
                    print(f"phase1: FAIL — gauge {needle} missing from "
                          "the owner's scrape", file=sys.stderr)
                    return 1
            snap = _fetch(owner, "/snapshot")
            if "load" not in snap or snap["load"]["heat_skew"] < 1.0:
                print("phase1: FAIL — /snapshot missing the load "
                      "section", file=sys.stderr)
                return 1
            hz = _fetch(owner, "/healthz")
            if "load" not in hz \
                    or "heat_ms" not in hz["shards"][str(hot)]:
                print("phase1: FAIL — /healthz missing the heat "
                      "columns", file=sys.stderr)
                return 1

            # per-study cost column + the wave correlation field
            rows = _study_rows(urls)
            hot_row = rows.get(hot_sids[0])
            # `asks` counts device-wave rows only (startup rand asks
            # never reach the wave chokepoint): budget 4 = 2 startup +
            # 2 device asks, and all 4 tells
            if not hot_row or "load" not in hot_row \
                    or hot_row["load"]["tells"] < 4 \
                    or hot_row["load"]["device_ms"] <= 0:
                print(f"phase1: FAIL — /studies row lacks the cost "
                      f"column: {hot_row}", file=sys.stderr)
                return 1
            req = urllib.request.Request(
                owner + "/ask",
                data=json.dumps({"study_id": hot_sids[0]}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as r:
                ans = json.loads(r.read())
            if ans.get("wave") is None:
                print(f"phase1: FAIL — /ask answer lacks the wave "
                      f"field: {sorted(ans)}", file=sys.stderr)
                return 1
            client.tell(hot_sids[0], ans["trials"][0]["tid"], loss=1.0)

            # zero lost tells: every driven study holds exactly its
            # budget, none pending (the extra wave-lint trial included)
            rows = _study_rows(urls)
            lost = []
            for sid in hot_sids + list(cold_sids.values()):
                want = 5 if sid == hot_sids[0] else 4
                s = rows.get(sid)
                if not s or s["n_trials"] != want or s["n_pending"]:
                    lost.append((sid, s and s["n_trials"],
                                 s and s["n_pending"]))
            if lost:
                print(f"phase1: FAIL — lost tells: {lost}",
                      file=sys.stderr)
                return 1
            print(f"phase1: PASS — skew {fl['heat_skew']}x on "
                  f"/fleet/load, gauges lint clean, zero lost tells "
                  f"({len(rows)} studies)")
            return 0
        finally:
            for proc in procs:
                if proc is not None and proc.poll() is None:
                    proc.kill()
                    proc.wait()


def phase2_heat_follows_the_shard():
    from hyperopt_tpu.service import shard_of

    n_shards = 6
    print("load_smoke: phase 2 — volunteer handoff drains the hottest "
          "shard; SIGKILL replays the ledger; zero lost tells")
    with tempfile.TemporaryDirectory() as store:
        procs, urls = [], []
        try:
            for i in range(2):
                proc, url = _launch(store, f"q{i}", n_shards)
                if url is None:
                    print(f"phase2: FAIL — replica q{i} never announced",
                          file=sys.stderr)
                    return 1
                procs.append(proc)
                urls.append(url)
            if not wait_coverage(urls, timeout=60):
                print("phase2: FAIL — fleet never covered the keyspace",
                      file=sys.stderr)
                return 1
            held0 = _held(urls[0])
            if len(held0) < 2:
                print(f"phase2: FAIL — q0 holds {held0}, want ≥2 of "
                      f"{n_shards}", file=sys.stderr)
                return 1
            # home the hot study on one of q0's shards and burn heat
            client = _client(urls)
            sid = hot = None
            for seed in range(200):
                cand = client.create_study(space=SPEC, seed=2000 + seed,
                                           n_startup_jobs=2,
                                           max_trials=30)
                if shard_of(cand, n_shards) in held0:
                    sid, hot = cand, shard_of(cand, n_shards)
                    break
            if sid is None:
                print("phase2: FAIL — no study landed on q0",
                      file=sys.stderr)
                return 1
            _drive(client, sid, 12)
            # the steward may have rebalanced during convergence — pin
            # the shard's CURRENT owner, then watch that replica
            owner0 = next((u for u in urls if hot in _held(u)), None)
            if owner0 is None:
                print(f"phase2: FAIL — shard {hot} unowned after "
                      "driving", file=sys.stderr)
                return 1
            heat0 = _fetch(owner0, "/healthz")["shards"][
                str(hot)]["heat_ms"]
            if heat0 <= 0:
                print("phase2: FAIL — no heat attributed to the hot "
                      "shard before the handoff", file=sys.stderr)
                return 1
            held0 = _held(owner0)

            # the joiner makes q0 overfull: the volunteer handoff must
            # release the HOTTEST shard, not the highest-numbered one
            proc, url = _launch(store, "q2", n_shards)
            if url is None:
                print("phase2: FAIL — q2 never announced",
                      file=sys.stderr)
                return 1
            procs.append(proc)
            urls.append(url)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                now0 = _held(owner0)
                if len(now0) < len(held0):
                    break
                time.sleep(0.25)
            else:
                print("phase2: FAIL — the hot owner never volunteered "
                      "a shard", file=sys.stderr)
                return 1
            if hot in now0:
                print(f"phase2: FAIL — the owner released "
                      f"{held0 - now0}, but the hottest shard {hot} "
                      "stayed (heat-aware ordering broken)",
                      file=sys.stderr)
                return 1
            # graceful-handoff inheritance: the adopter shows the shard
            # arriving with its accumulated heat
            deadline = time.monotonic() + 60
            owner = None
            while time.monotonic() < deadline and owner is None:
                for u in urls:
                    if hot in _held(u):
                        owner = u
                        break
                time.sleep(0.25)
            if owner is None:
                print(f"phase2: FAIL — shard {hot} never re-adopted",
                      file=sys.stderr)
                return 1
            h = _fetch(owner, "/healthz")["shards"][str(hot)]
            if h["heat_ms"] < heat0 * 0.99:
                print(f"phase2: FAIL — adopter heat {h['heat_ms']} < "
                      f"pre-handoff {heat0}: inheritance lost",
                      file=sys.stderr)
                return 1
            print(f"phase2: handoff drained hottest shard {hot} "
                  f"(heat {heat0:.0f}ms) and the adopter inherited it")

            # now the SIGKILL path: no drain, no handoff record — the
            # durable ledger is all that survives
            _drive(client, sid, 4)
            time.sleep(2.5)               # let a heat roll land
            pre = _fetch(owner, "/healthz")["shards"][str(hot)]["heat_ms"]
            victim = urls.index(owner)
            procs[victim].send_signal(signal.SIGKILL)
            procs[victim].wait()
            procs[victim] = None
            live = [u for u, p in zip(urls, procs) if p is not None]
            deadline = time.monotonic() + 90
            new_owner = None
            while time.monotonic() < deadline and new_owner is None:
                for u in live:
                    try:
                        if hot in _held(u):
                            new_owner = u
                            break
                    except Exception:  # noqa: BLE001
                        pass
                time.sleep(0.25)
            if new_owner is None:
                print(f"phase2: FAIL — shard {hot} never reclaimed "
                      "after SIGKILL", file=sys.stderr)
                return 1
            h2 = _fetch(new_owner, "/healthz")["shards"][str(hot)]
            if h2["heat_ms"] < heat0 * 0.99:
                print(f"phase2: FAIL — post-SIGKILL heat "
                      f"{h2['heat_ms']} < {heat0}: the ledger did not "
                      "replay", file=sys.stderr)
                return 1
            # and serving continues: more trials, zero lost tells
            client2 = _client(live)
            _drive(client2, sid, 4)
            rows = _study_rows(live)
            s = rows.get(sid)
            if not s or s["n_trials"] != 20 or s["n_pending"]:
                print(f"phase2: FAIL — lost tells across the kill: {s}",
                      file=sys.stderr)
                return 1
            print(f"phase2: PASS — heat followed shard {hot} through a "
                  f"graceful handoff AND a SIGKILL (ledger heat "
                  f"{h2['heat_ms']:.0f}ms ≥ pre-kill {pre:.0f}ms "
                  "baseline), 20/20 tells settled")
            return 0
        finally:
            for proc in procs:
                if proc is not None and proc.poll() is None:
                    proc.kill()
                    proc.wait()


def main():
    for phase in (phase1_skew_surfaces, phase2_heat_follows_the_shard):
        rc = phase()
        if rc:
            return rc
    print("load_smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
