"""Perf-regression gate over the repo's bench trajectory.

Compares the newest ``BENCH_r*.json`` against the previous one with
per-metric relative thresholds and exits non-zero on a regression, so a PR
that quietly slows the hot path fails loudly instead of shipping.  Opt in
from the test runner with ``BENCH_GATE=1 ./run_tests.sh``.

What gets compared (all higher-is-better throughputs):

* the headline ``parsed`` record — ``value`` (candidates/sec) and
  ``vs_baseline`` — always, when both rounds carry one;
* stage-level throughput sequences (``trials_per_sec``,
  ``candidates_per_sec``, ``cv_fits_per_sec``) regex-mined from the
  recorded output tail, compared positionally ONLY when both rounds report
  the same number of occurrences (a round that adds or drops a stage would
  otherwise misalign the comparison — those names are skipped with a note
  instead of guessed at);
* lower-is-better latency/memory keys (``ask_p*_ms`` from the ask_latency
  stage, ``peak_hbm_bytes``/``history_bytes`` from the devmem stage) gated
  on the allowed relative RISE instead.

The no-baseline case (fewer than two ``BENCH_r*.json`` — a fresh repo with
an empty bench trajectory) records what the newest round reports and
passes: the gate's job is to compare rounds, not to manufacture one.

Shared-hardware noise note: these benches run on a tunneled, contended
chip; the default 20% threshold (35% for ``vs_baseline``, whose numpy
denominator is itself noisy) is deliberately loose.  Override per run with
``--threshold``.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

# metric-name → allowed relative drop (new >= prev * (1 - threshold));
# for the LOWER_IS_BETTER latency metrics the same threshold bounds the
# allowed relative rise instead (new <= prev * (1 + threshold))
DEFAULT_THRESHOLDS = {
    "headline.value": 0.20,
    "headline.vs_baseline": 0.35,
    "trials_per_sec": 0.20,
    "candidates_per_sec": 0.20,
    "cv_fits_per_sec": 0.20,
    # the sharded fused tell+ask (bench.py sharded_suggest stage): one
    # occurrence per shard count {1,2,4,8}, compared positionally; a
    # regression here means the mesh path stopped scaling
    "sharded_cand_per_sec": 0.20,
    # per-ask wall latency (bench.py ask_latency stage): shared contended
    # hardware makes tails noisy — p50 gates tightest, p99 loosest
    "ask_p50_ms": 0.35,
    "ask_p95_ms": 0.50,
    "ask_p99_ms": 1.00,
    # peak device memory (bench.py devmem stage): a leaked cap-sized
    # buffer shows up as a step, so the allowed rise is moderate; the
    # history census is near-deterministic for a fixed config, hence tight
    "peak_hbm_bytes": 0.30,
    "history_bytes": 0.10,
}

_TAIL_METRICS = ("trials_per_sec", "candidates_per_sec", "cv_fits_per_sec",
                 "sharded_cand_per_sec",
                 "ask_p50_ms", "ask_p95_ms", "ask_p99_ms",
                 "peak_hbm_bytes", "history_bytes")

# latency and peak-memory metrics regress UPWARD
LOWER_IS_BETTER = ("ask_p50_ms", "ask_p95_ms", "ask_p99_ms",
                   "peak_hbm_bytes", "history_bytes")


def bench_files(root):
    """BENCH_r*.json in round order (numeric suffix)."""

    def round_no(path):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        return int(m.group(1)) if m else -1

    return sorted(glob.glob(os.path.join(root, "BENCH_r*.json")),
                  key=round_no)


def extract_metrics(path):
    """``{metric name: value}`` for the headline record plus
    ``{name: [occurrences]}`` sequences mined from the output tail."""
    with open(path) as f:
        rec = json.load(f)
    scalars = {}
    parsed = rec.get("parsed") or {}
    if isinstance(parsed.get("value"), (int, float)):
        scalars["headline.value"] = float(parsed["value"])
    if isinstance(parsed.get("vs_baseline"), (int, float)):
        scalars["headline.vs_baseline"] = float(parsed["vs_baseline"])
    tail = rec.get("tail", "") or ""
    sequences = {}
    for name in _TAIL_METRICS:
        vals = re.findall(rf'"{name}":\s*(-?[0-9][0-9.eE+-]*)', tail)
        if vals:
            sequences[name] = [float(v) for v in vals]
    return scalars, sequences


def compare(prev, new, thresholds):
    """Returns ``(regressions, notes)`` — regressions is a list of
    human-readable failure lines."""
    regressions, notes = [], []
    p_scalars, p_seqs = prev
    n_scalars, n_seqs = new

    def check(name, pv, nv):
        base = name.split("[")[0]
        thr = thresholds.get(base, thresholds.get("default", 0.20))
        if base in LOWER_IS_BETTER:
            ceil = pv * (1.0 + thr)
            if nv > ceil:
                regressions.append(
                    f"{name}: {nv:.6g} > {pv:.6g} * (1 + {thr:.0%}) "
                    f"= {ceil:.6g}")
            else:
                notes.append(f"{name}: {pv:.6g} -> {nv:.6g}  ok (lower=better)")
            return
        floor = pv * (1.0 - thr)
        if nv < floor:
            regressions.append(
                f"{name}: {nv:.6g} < {pv:.6g} * (1 - {thr:.0%}) = {floor:.6g}")
        else:
            notes.append(f"{name}: {pv:.6g} -> {nv:.6g}  ok")

    for name in sorted(set(p_scalars) & set(n_scalars)):
        check(name, p_scalars[name], n_scalars[name])
    for name in sorted(set(p_seqs) & set(n_seqs)):
        pv, nv = p_seqs[name], n_seqs[name]
        if len(pv) != len(nv):
            notes.append(f"{name}: occurrence count changed "
                         f"({len(pv)} -> {len(nv)}), skipping positional "
                         "comparison")
            continue
        for i, (a, b) in enumerate(zip(pv, nv)):
            check(f"{name}[{i}]", a, b)
    return regressions, notes


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python scripts/bench_gate.py",
        description="Fail on a perf regression between the two newest "
                    "BENCH_r*.json rounds.")
    p.add_argument("--dir", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="directory holding BENCH_r*.json (default: repo root)")
    p.add_argument("--threshold", type=float, default=None,
                   help="override every per-metric relative threshold")
    args = p.parse_args(argv)

    thresholds = dict(DEFAULT_THRESHOLDS)
    if args.threshold is not None:
        thresholds = {k: args.threshold for k in thresholds}
        thresholds["default"] = args.threshold

    files = bench_files(args.dir)
    if len(files) < 2:
        if files:
            scalars, seqs = extract_metrics(files[0])
            print(f"bench gate: no baseline ({len(files)} round recorded); "
                  "recording and passing")
            for k, v in sorted(scalars.items()):
                print(f"  {k} = {v:.6g}")
            for k, v in sorted(seqs.items()):
                print(f"  {k}: {len(v)} occurrence(s)")
        else:
            print("bench gate: bench trajectory is empty; passing")
        return 0

    prev_path, new_path = files[-2], files[-1]
    try:
        prev = extract_metrics(prev_path)
        new = extract_metrics(new_path)
    except (OSError, ValueError) as e:
        print(f"bench gate: cannot parse bench artifacts: {e}",
              file=sys.stderr)
        return 2
    regressions, notes = compare(prev, new, thresholds)
    print(f"bench gate: {os.path.basename(prev_path)} -> "
          f"{os.path.basename(new_path)}")
    for line in notes:
        print("  " + line)
    if regressions:
        print("bench gate: REGRESSION", file=sys.stderr)
        for line in regressions:
            print("  " + line, file=sys.stderr)
        return 1
    if not notes:
        print("  (no comparable metrics between the two rounds)")
    print("bench gate: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
