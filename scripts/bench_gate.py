"""Perf-regression gate over the repo's bench trajectory.

**Windowed mode (default when the trajectory store exists).**  The
append-only store ``.obs/trajectory.jsonl`` (obs/trajectory.py — bench.py
appends one record per run; ``python -m hyperopt_tpu.obs.trajectory
backfill`` seeds it from the checked-in ``BENCH_r*.json``) holds one
record per bench run.  The gate compares the NEWEST record against the
**median of the previous K runs** (``--window``, default 5), per key,
with explicit direction metadata from
``hyperopt_tpu.obs.trajectory.KEY_DIRECTIONS`` — higher-is-better
throughputs gate the allowed relative drop, lower-is-better
latency/memory keys gate the allowed relative rise, and absolute keys
(``profiler_overhead_frac``) gate the raw value against a FIXED bar
(median-relative would ratchet).  A windowed median
is robust to the single noisy round that a pairwise newest-vs-previous
compare mistakes for a regression (or, worse, adopts as the new
baseline).  Keys the direction table doesn't know are recorded but never
gate.  History is **backend-matched**: the newest record only gates
against stored runs with the same ``backend`` (a CPU dev-box run neither
fails against nor poisons the TPU history; with no same-backend history
every key records as "no history yet" and the gate passes).

**Legacy mode** (``--legacy``, or automatically when the store is missing
or holds fewer than two records) compares the newest ``BENCH_r*.json``
against the previous one, exactly the pre-windowed behavior.

Opt in from the test runner with ``BENCH_GATE=1 ./run_tests.sh``.  The
no-history case records what the newest round reports and passes: the
gate's job is to compare runs, not to manufacture one.

Shared-hardware noise note: these benches run on a tunneled, contended
chip; the default thresholds (20% throughputs, 35-100% latency tails) are
deliberately loose.  Override per run with ``--threshold``.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import statistics
import sys

# the gate must never claim the ambient TPU: force CPU before any
# hyperopt_tpu import can pull jax in
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# metric-name → allowed relative drop (new >= prev * (1 - threshold));
# for the LOWER_IS_BETTER latency metrics the same threshold bounds the
# allowed relative rise instead (new <= prev * (1 + threshold))
DEFAULT_THRESHOLDS = {
    "headline.value": 0.20,
    "headline.vs_baseline": 0.35,
    "trials_per_sec": 0.20,
    "candidates_per_sec": 0.20,
    "cv_fits_per_sec": 0.20,
    # the sharded fused tell+ask (bench.py sharded_suggest stage): one
    # occurrence per shard count {1,2,4,8}, compared positionally; a
    # regression here means the mesh path stopped scaling
    "sharded_cand_per_sec": 0.20,
    # per-ask wall latency (bench.py ask_latency stage): shared contended
    # hardware makes tails noisy — p50 gates tightest, p99 loosest
    "ask_p50_ms": 0.35,
    "ask_p95_ms": 0.50,
    "ask_p99_ms": 1.00,
    # peak device memory (bench.py devmem stage): a leaked cap-sized
    # buffer shows up as a step, so the allowed rise is moderate; the
    # history census is near-deterministic for a fixed config, hence tight
    "peak_hbm_bytes": 0.30,
    "history_bytes": 0.10,
    # multi-study serving throughput (bench.py multi_study stage)
    "studies_per_sec": 0.25,
    "study_ask_p99_ms": 1.00,
    "slot_utilization_frac": 0.15,
    # durable serving plane (bench.py service_resume stage): restart
    # availability gap (compile-dominated, loose) and the 2x-capacity
    # shed fraction (a collapse toward zero = backpressure broke)
    "resume_latency_sec": 1.00,
    "shed_rate_frac": 0.60,
    # replicated serving fleet (bench.py fleet_scale stage): throughput
    # at the largest replica count, and the shard reclaim/adopt latency
    "fleet_studies_per_sec": 0.35,
    "reclaim_latency_sec": 1.00,
    # cold-start compile plane (bench.py coldstart stage, ISSUE 14)
    "cold_study_ask_p99_ms": 1.00,
    "compile_queue_depth_max": 2.00,
    "bank_hit_frac": 0.40,
}

_TAIL_METRICS = ("trials_per_sec", "candidates_per_sec", "cv_fits_per_sec",
                 "sharded_cand_per_sec",
                 "ask_p50_ms", "ask_p95_ms", "ask_p99_ms",
                 "peak_hbm_bytes", "history_bytes",
                 "studies_per_sec", "study_ask_p99_ms",
                 "slot_utilization_frac",
                 "resume_latency_sec", "shed_rate_frac",
                 "fleet_studies_per_sec", "reclaim_latency_sec",
                 "cold_study_ask_p99_ms", "compile_queue_depth_max",
                 "bank_hit_frac")

# latency and peak-memory metrics regress UPWARD
LOWER_IS_BETTER = ("ask_p50_ms", "ask_p95_ms", "ask_p99_ms",
                   "study_ask_p99_ms",
                   "peak_hbm_bytes", "history_bytes",
                   "resume_latency_sec", "reclaim_latency_sec",
                   "cold_study_ask_p99_ms", "compile_queue_depth_max")


def bench_files(root):
    """BENCH_r*.json in round order (numeric suffix)."""

    def round_no(path):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        return int(m.group(1)) if m else -1

    return sorted(glob.glob(os.path.join(root, "BENCH_r*.json")),
                  key=round_no)


def extract_metrics(path):
    """``{metric name: value}`` for the headline record plus
    ``{name: [occurrences]}`` sequences mined from the output tail."""
    with open(path) as f:
        rec = json.load(f)
    scalars = {}
    parsed = rec.get("parsed") or {}
    if isinstance(parsed.get("value"), (int, float)):
        scalars["headline.value"] = float(parsed["value"])
    if isinstance(parsed.get("vs_baseline"), (int, float)):
        scalars["headline.vs_baseline"] = float(parsed["vs_baseline"])
    tail = rec.get("tail", "") or ""
    sequences = {}
    for name in _TAIL_METRICS:
        vals = re.findall(rf'"{name}":\s*(-?[0-9][0-9.eE+-]*)', tail)
        if vals:
            sequences[name] = [float(v) for v in vals]
    return scalars, sequences


def compare(prev, new, thresholds):
    """Returns ``(regressions, notes)`` — regressions is a list of
    human-readable failure lines."""
    regressions, notes = [], []
    p_scalars, p_seqs = prev
    n_scalars, n_seqs = new

    def check(name, pv, nv):
        base = name.split("[")[0]
        thr = thresholds.get(base, thresholds.get("default", 0.20))
        if base in LOWER_IS_BETTER:
            ceil = pv * (1.0 + thr)
            if nv > ceil:
                regressions.append(
                    f"{name}: {nv:.6g} > {pv:.6g} * (1 + {thr:.0%}) "
                    f"= {ceil:.6g}")
            else:
                notes.append(f"{name}: {pv:.6g} -> {nv:.6g}  ok (lower=better)")
            return
        floor = pv * (1.0 - thr)
        if nv < floor:
            regressions.append(
                f"{name}: {nv:.6g} < {pv:.6g} * (1 - {thr:.0%}) = {floor:.6g}")
        else:
            notes.append(f"{name}: {pv:.6g} -> {nv:.6g}  ok")

    for name in sorted(set(p_scalars) & set(n_scalars)):
        check(name, p_scalars[name], n_scalars[name])
    for name in sorted(set(p_seqs) & set(n_seqs)):
        pv, nv = p_seqs[name], n_seqs[name]
        if len(pv) != len(nv):
            notes.append(f"{name}: occurrence count changed "
                         f"({len(pv)} -> {len(nv)}), skipping positional "
                         "comparison")
            continue
        for i, (a, b) in enumerate(zip(pv, nv)):
            check(f"{name}[{i}]", a, b)
    return regressions, notes


def windowed_compare(history, new, directions, window=5, override=None,
                     explain=False):
    """Newest trajectory record vs the windowed median of its history.

    ``history``/``new`` are obs/trajectory.py record dicts (oldest-first
    history, excluding ``new``).  ``directions`` is the
    ``KEY_DIRECTIONS`` table: ``{key: {direction, threshold[, absolute]}}``
    — an unknown key is recorded in the notes but never gates.
    ``explain`` adds one note line per gated key showing exactly which
    window values fed the median and the bound that was applied.
    Returns ``(regressions, notes)``.
    """
    regressions, notes = [], []
    hist = history[-window:]

    def check(label, key, nv, values):
        meta = directions.get(key)
        if meta is None:
            notes.append(f"{label}: {nv:.6g}  (ungated key, recorded only)")
            return
        thr = override if override is not None else meta["threshold"]
        direction = meta.get("direction", "higher")
        if explain:
            win = ", ".join(f"{v:.6g}" for v in values) or "(none)"
            notes.append(f"{label}: window[{len(values)}] = [{win}]  "
                         f"threshold {thr:.6g} "
                         f"({'absolute' if meta.get('absolute') else 'relative'}, "
                         f"{direction}=better)")
        if meta.get("absolute"):
            # FIXED bar, not median-relative: an overhead fraction gated
            # vs its own history would ratchet (~thr per window shift)
            # instead of staying pinned at the documented absolute bound.
            # Needs no history, so it gates from the very first run.
            lo, hi = -thr, thr
            bound_txt = f"fixed bar ±{thr:.6g} (absolute)"
        else:
            med = statistics.median(values)
            if med == 0:
                # a zero median (e.g. history_bytes on a backend where
                # memory_stats is None) makes every relative bound
                # degenerate — any nonzero value would gate regardless of
                # threshold, so going from unmeasured-zero to measured
                # must record, not fail
                notes.append(f"{label}: {nv:.6g}  (history median is 0 — "
                             "relative bound undefined, recording only)")
                return
            lo, hi = med * (1.0 - thr), med * (1.0 + thr)
            bound_txt = f"median {med:.6g} ± {thr:.0%}"
        if direction == "higher" and nv < lo:
            regressions.append(
                f"{label}: {nv:.6g} < {lo:.6g}  [{bound_txt} over "
                f"{len(values)} run(s), higher=better]")
        elif direction == "lower" and nv > hi:
            regressions.append(
                f"{label}: {nv:.6g} > {hi:.6g}  [{bound_txt} over "
                f"{len(values)} run(s), lower=better]")
        else:
            notes.append(f"{label}: {nv:.6g}  ok vs {bound_txt} "
                         f"({len(values)} run(s), {direction}=better)")

    # every scalar key gates against the windowed median of whatever
    # history carries it: the headline values (value, vs_baseline) and
    # each tail metric's representative view (bench.py names its own
    # exactly via keys_override; backfilled rounds fall back to first
    # tail occurrence — noisier, but the median absorbs a mislabeled
    # round where skipping would mean the key NEVER gates, since real
    # histories rarely keep identical series shapes across PRs for the
    # positional pass below)
    new_series = new.get("series") or {}
    for key, nv in sorted((new.get("keys") or {}).items()):
        if not isinstance(nv, (int, float)):
            continue
        values = [(r.get("keys") or {}).get(key) for r in hist]
        values = [v for v in values if isinstance(v, (int, float))]
        if not values and not (directions.get(key) or {}).get("absolute"):
            # absolute fixed-bar keys gate even without history
            notes.append(f"{key}: {nv:.6g}  (no history yet, recording)")
            continue
        check(key, key, nv, values)
    # tail-mined / repeating metrics (one occurrence per shard count, per
    # algo, per stage): windowed per position, over history runs with the
    # SAME occurrence count — a run that added or dropped a stage (or a
    # differently-truncated recorded tail) never misaligns the gate
    for key, nseq in sorted(new_series.items()):
        if not isinstance(nseq, list) or not nseq:
            continue
        if len(nseq) == 1 and key in (new.get("keys") or {}):
            # the scalar pass above already gated this key, possibly
            # against a DIFFERENT value (keys_override names the
            # representative; the tail miner only knows text order) —
            # a second verdict under the identical label would be
            # untraceable
            continue
        hseqs = [(r.get("series") or {}).get(key) for r in hist]
        hseqs = [s for s in hseqs
                 if isinstance(s, list) and len(s) == len(nseq)]
        if not hseqs:
            if (directions.get(key) or {}).get("absolute"):
                # fixed-bar keys need no history: gate each occurrence
                for i in range(len(nseq)):
                    label = f"{key}[{i}]" if len(nseq) > 1 else key
                    check(label, key, nseq[i], [])
                continue
            notes.append(f"{key}: occurrence count {len(nseq)} has no "
                         "matching history, skipping positional gate")
            continue
        for i in range(len(nseq)):
            label = f"{key}[{i}]" if len(nseq) > 1 else key
            check(label, key, nseq[i], [s[i] for s in hseqs])
    return regressions, notes


def _windowed_main(store, window, override, explain=False):
    """Gate the store's newest record against its windowed history.
    Returns an exit code, or None to fall back to legacy mode."""
    from hyperopt_tpu.obs.trajectory import KEY_DIRECTIONS, load

    records = [r for r in load(store) if r.get("kind") == "bench"]
    if len(records) < 2:
        return None  # not enough trajectory: legacy pairwise compare
    new, history = records[-1], records[:-1]
    # throughput/latency figures are only comparable on the same backend:
    # a CPU dev-box run must not gate against (or poison the median of)
    # the TPU history.  No same-backend history → every key records as
    # "no history yet" and the gate passes, building the new backend's
    # window from here.
    backend = new.get("backend")
    skipped = len(history)
    history = [r for r in history if r.get("backend") == backend]
    skipped -= len(history)
    regressions, notes = windowed_compare(
        history, new, KEY_DIRECTIONS, window=window, override=override,
        explain=explain)
    n_win = min(window, len(history))
    print(f"bench gate (windowed): {new.get('source', '?')} "
          f"vs median of last {n_win} of {len(history)} "
          f"backend={backend or '?'} run(s)"
          + (f" ({skipped} other-backend run(s) excluded)" if skipped
             else "")
          + f" [{os.path.relpath(store)}]")
    for line in notes:
        print("  " + line)
    if regressions:
        print("bench gate: REGRESSION", file=sys.stderr)
        for line in regressions:
            print("  " + line, file=sys.stderr)
        return 1
    if not notes:
        print("  (newest record carries no gateable keys)")
    print("bench gate: ok")
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python scripts/bench_gate.py",
        description="Fail on a perf regression: newest bench run vs the "
                    "windowed median of the trajectory store (fallback: "
                    "the two newest BENCH_r*.json rounds).")
    p.add_argument("--dir", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="directory holding BENCH_r*.json (default: repo root)")
    p.add_argument("--threshold", type=float, default=None,
                   help="override every per-metric relative threshold")
    p.add_argument("--store", default=None,
                   help="trajectory store path (default: "
                        "<dir>/.obs/trajectory.jsonl)")
    p.add_argument("--window", type=int, default=5,
                   help="windowed mode: how many prior runs feed the "
                        "median (default 5)")
    p.add_argument("--legacy", action="store_true",
                   help="force the pairwise newest-vs-previous "
                        "BENCH_r*.json compare")
    p.add_argument("--explain", action="store_true",
                   help="windowed mode: print, per gated key, the exact "
                        "window values, median and bound it compared "
                        "against")
    args = p.parse_args(argv)

    if not args.legacy:
        store = args.store or os.path.join(args.dir, ".obs",
                                           "trajectory.jsonl")
        if os.path.exists(store):
            rc = _windowed_main(store, args.window, args.threshold,
                                explain=args.explain)
            if rc is not None:
                return rc
            print("bench gate: trajectory store has <2 records; falling "
                  "back to the pairwise BENCH_r*.json compare")

    thresholds = dict(DEFAULT_THRESHOLDS)
    if args.threshold is not None:
        thresholds = {k: args.threshold for k in thresholds}
        thresholds["default"] = args.threshold

    files = bench_files(args.dir)
    if len(files) < 2:
        if files:
            scalars, seqs = extract_metrics(files[0])
            print(f"bench gate: no baseline ({len(files)} round recorded); "
                  "recording and passing")
            for k, v in sorted(scalars.items()):
                print(f"  {k} = {v:.6g}")
            for k, v in sorted(seqs.items()):
                print(f"  {k}: {len(v)} occurrence(s)")
        else:
            print("bench gate: bench trajectory is empty; passing")
        return 0

    prev_path, new_path = files[-2], files[-1]
    try:
        prev = extract_metrics(prev_path)
        new = extract_metrics(new_path)
    except (OSError, ValueError) as e:
        print(f"bench gate: cannot parse bench artifacts: {e}",
              file=sys.stderr)
        return 2
    regressions, notes = compare(prev, new, thresholds)
    print(f"bench gate: {os.path.basename(prev_path)} -> "
          f"{os.path.basename(new_path)}")
    for line in notes:
        print("  " + line)
    if regressions:
        print("bench gate: REGRESSION", file=sys.stderr)
        for line in regressions:
            print("  " + line, file=sys.stderr)
        return 1
    if not notes:
        print("  (no comparable metrics between the two rounds)")
    print("bench gate: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
