"""QUALITY_GATE end-to-end smoke (ISSUE 16): the search-quality
observability plane against a REAL subprocess server.

What it pins (the cross-process slice no in-process test can):

* a real ``python -m hyperopt_tpu.service.server`` subprocess with WAL
  store and the quality plane armed (the default) serves a small zoo
  mix under BOTH algorithms the wire offers — tpe (the serving
  default) and rand (startup floor ≥ budget) — with the objective
  evaluated client-side from the same ``zoo.ZOO`` entry the server
  resolved the study's target from;
* the server's OWN telemetry ranks them: summed trials-to-target over
  the mix (unsolved arms count the full budget), read from the quality
  section ``GET /studies`` carries, must be no worse for tpe than for
  rand — the smoke-scale version of the bench ``search_quality`` bars;
* a deliberately budget-starved study (constant losses past the plateau
  window) raises its ``stagnant`` flag on ``/studies`` AND lands a
  ``stagnation`` event on ``GET /study/<id>/timeline``;
* ``GET /metrics`` passes the Prometheus exposition lint and carries
  the ``hyperopt_tpu_quality_*`` gauge families (plus the stagnation
  SLO objective riding the burn-rate plane);
* the server still drains cleanly on SIGTERM (exit 0).

Opt in via ``QUALITY_GATE=1 ./run_tests.sh``.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

#: mix size 4 keeps the smoke to the cheap analytic domains
#: (quadratic1, branin, hartmann6, rosenbrock4 — all budget 20)
_MIX_N = 4


def fail(msg):
    print(f"quality_smoke: FAIL — {msg}", file=sys.stderr)
    return 1


def _drive_study(client, zoo_rec, sid, budget):
    """Ask/tell ``sid`` to budget, evaluating the zoo objective
    client-side (the server never sees a loss it didn't get told)."""
    for _ in range(budget):
        t = client.ask(sid)[0]
        loss = float(zoo_rec.objective(t["params"]))
        client.tell(sid, t["tid"], loss=loss)


def main():
    from validate_scrape import validate_metrics_text

    from hyperopt_tpu.obs.quality import DEFAULT_PLATEAU_WINDOW
    from hyperopt_tpu.service.client import ServiceClient
    from hyperopt_tpu.zoo import ZOO, make_study_mix

    tmp = tempfile.mkdtemp(prefix="quality_smoke_")
    store = os.path.join(tmp, "store")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("HYPEROPT_TPU_QUALITY", None)       # default ON is the pin
    env["HYPEROPT_TPU_SERVICE_SLO"] = "on"
    proc = subprocess.Popen(
        [sys.executable, "-m", "hyperopt_tpu.service.server",
         "--port", "0", "--announce", "--store", store],
        cwd=_REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    url = None
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if line.startswith("SERVICE_URL "):
                url = line.split(None, 1)[1].strip()
                break
            if proc.poll() is not None:
                break
        if url is None:
            print((proc.stderr.read() or "")[-2000:], file=sys.stderr)
            return fail("server never announced")
        print(f"quality_smoke: server up at {url} (pid {proc.pid})")

        client = ServiceClient(url)
        import urllib.request

        # -- the zoo mix under tpe AND rand --------------------------------
        items = make_study_mix(_MIX_N, 0)
        arms = {}  # (algo, item name) -> sid
        for m in items:
            # tpe arm: the mix's startup count; rand arm: startup floor
            # past the budget, so every ask is served by rand
            arms["tpe", m.name] = client.create_study(
                zoo=m.domain.name, seed=m.seed,
                n_startup_jobs=m.n_startup_jobs)
            arms["rand", m.name] = client.create_study(
                zoo=m.domain.name, seed=m.seed,
                n_startup_jobs=m.budget + 1)
        for m in items:
            for algo in ("tpe", "rand"):
                _drive_study(client, ZOO[m.domain.name],
                             arms[algo, m.name], m.budget)
        with urllib.request.urlopen(url + "/studies", timeout=30) as r:
            studies = {s["study_id"]: s
                       for s in json.loads(r.read())["studies"]}
        t2t = {"tpe": 0, "rand": 0}
        for m in items:
            for algo in ("tpe", "rand"):
                s = studies.get(arms[algo, m.name]) or {}
                q = s.get("quality")
                if not q:
                    return fail(f"study {arms[algo, m.name]} ({algo} "
                                f"{m.name}) has no quality section: {s}")
                if q.get("best_loss") is None or q.get("n_told") != m.budget:
                    return fail(f"quality bookkeeping off for {algo} "
                                f"{m.name}: {q}")
                t2t[algo] += (q["trials_to_target"] if q.get("solved")
                              else m.budget)
        print(f"quality_smoke: mix of {len(items)} driven under both "
              f"algos — trials-to-target tpe {t2t['tpe']} vs rand "
              f"{t2t['rand']}")
        if t2t["tpe"] > t2t["rand"]:
            return fail(f"tpe ({t2t['tpe']}) worse than rand "
                        f"({t2t['rand']}) on summed trials-to-target")

        # -- stagnation fires on a budget-starved study --------------------
        sid = client.create_study(
            space={"x": {"dist": "uniform", "args": [-5, 5]}}, seed=3,
            n_startup_jobs=1)
        for _ in range(DEFAULT_PLATEAU_WINDOW + 2):
            t = client.ask(sid)[0]
            client.tell(sid, t["tid"], loss=1.0)  # never improves
        with urllib.request.urlopen(url + "/studies", timeout=30) as r:
            studies = {s["study_id"]: s
                       for s in json.loads(r.read())["studies"]}
        q = (studies.get(sid) or {}).get("quality") or {}
        if not q.get("stagnant"):
            return fail(f"budget-starved study never flagged stagnant: {q}")
        with urllib.request.urlopen(f"{url}/study/{sid}/timeline",
                                    timeout=30) as r:
            tl = json.loads(r.read())
        ev = [e["event"] for e in tl.get("events", [])]
        if "stagnation" not in ev or "improvement" not in ev:
            return fail(f"timeline missing quality events: {ev}")
        print("quality_smoke: stagnation flagged on /studies and the "
              "timeline")

        # -- /metrics: exposition lint + quality_* families ----------------
        with urllib.request.urlopen(url + "/metrics", timeout=30) as r:
            text = r.read().decode()
        errs = validate_metrics_text(text)
        if errs:
            return fail("exposition lint: " + "; ".join(errs[:5]))
        for fam in ("hyperopt_tpu_quality_studies",
                    "hyperopt_tpu_quality_stagnant_frac",
                    "hyperopt_tpu_slo_stagnation_budget_remaining_frac"):
            if fam not in text:
                return fail(f"/metrics missing quality family {fam}")
        print("quality_smoke: /metrics lints clean with quality_* gauges "
              "and the stagnation SLO objective")

        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
        if rc != 0:
            return fail(f"server exited {rc} on SIGTERM")
        print("quality_smoke: OK — tpe beat rand on the mix by the "
              "server's own telemetry; stagnation detected end-to-end; "
              "quality_* gauges lint clean; clean drain")
        return 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()


if __name__ == "__main__":
    sys.exit(main())
