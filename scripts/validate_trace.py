#!/usr/bin/env python
"""Validate Chrome/Perfetto trace-event JSON emitted by ``obs.report
--export-trace`` (hyperopt_tpu/obs/export.py).

Checked invariants — the contract a trace viewer actually relies on:

* top level is ``{"traceEvents": [...]}`` (object form) or a bare event
  array;
* every event is an object with a known ``ph`` (``X i B E M C s t f``);
* request-trace flow events (``s``/``t``/``f`` — obs/export.py
  ``flow_events``, ISSUE 11): every flow id opens with exactly one
  ``s``, terminates with exactly one ``f`` (no dangling flows), and
  every flow event binds to an enclosing ``X`` slice on its
  ``(pid, tid)`` track;
* non-metadata events carry numeric ``ts`` >= 0 and integer ``pid``/``tid``;
* complete (``X``) events have ``dur`` >= 0;
* duration ``B``/``E`` events are matched per ``(pid, tid)`` track (no
  dangling begin, no end-without-begin);
* per ``(pid, tid)`` track, non-metadata events appear in non-decreasing
  ``ts`` file order (the exporter sorts; a violation means a broken merge);
* metadata (``M``) events precede all others (the exporter's layout).

Merged host+device artifacts (``obs.report --export-trace`` folds
``jax.profiler`` captures from obs/profiler.py into the host spans) add
three invariants:

* **track-group naming** — every ``pid`` that carries timeline events has
  a ``process_name`` metadata record (an unnamed device track group means
  the capture merge dropped its synthesized name);
* **counter-track monotonicity** — per ``(pid, tid, counter name)``,
  ``C`` events appear in non-decreasing ``ts`` order and every counter
  arg is numeric (an interleaved counter series plots as garbage);
* **annotation ids present** — device-timeline events named for the loop
  boundaries (``fmin.tick``, ``device.chunk``, ``driver.gen``) must carry
  their trial/generation ids, either as ``args`` or TraceMe-encoded in
  the name (``name#k=v#``) — a bare annotation means the id plumbing
  broke and kernels can no longer be attributed.

Exit 0 when every input validates, 1 otherwise, 2 on unreadable input.

``--self-test`` runs the whole pipeline end to end on CPU: a tiny armed
two-controller run (the ``fmin_multihost`` per-controller stream naming),
``obs.report --export-trace`` over the merged streams, then validation —
the opt-in CI gate ``TRACE_GATE=1 ./run_tests.sh`` wires this in next to
``bench_gate.py``.

``--profile-self-test`` is the device-capture round trip (``PROFILE_GATE=1
./run_tests.sh``): a child ``fmin`` runs with the capture plane + scrape
server armed, the parent triggers ``GET /profile?sec=1`` MID-RUN, and the
resulting artifact must merge with the host spans into a trace this
script accepts — device track groups, naming, annotations and all.
"""

from __future__ import annotations

import argparse
import json
import sys

_KNOWN_PH = {"X", "i", "I", "B", "E", "M", "C", "s", "t", "f"}

#: the loop-boundary annotation names obs/profiler.py stamps onto the
#: device timeline — events with these names must carry trial/generation
#: ids (as ``args`` or TraceMe-encoded ``name#k=v#``) or kernel
#: attribution is broken
ANNOTATION_NAMES = {"fmin.tick", "fmin.tick.speculative",
                    "device.chunk", "driver.gen"}


def validate_events(events):
    """Return a list of human-readable violations (empty = valid)."""
    errors = []
    if not isinstance(events, list):
        return ["traceEvents is not a list"]
    last_ts = {}  # (pid, tid) -> last seen ts
    counter_ts = {}  # (pid, tid, counter name) -> last seen ts
    begin_stack = {}  # (pid, tid) -> [names]
    named_pids = set()  # pids with a process_name metadata record
    event_pids = set()  # pids carrying timeline events
    seen_non_meta = False
    flow_events = {}  # flow id -> [(ph, ts, pid, tid, where)]
    slices = {}  # (pid, tid) -> [(start, end)] X-slice intervals
    for i, e in enumerate(events):
        where = f"event[{i}]"
        if not isinstance(e, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = e.get("ph")
        if ph not in _KNOWN_PH:
            errors.append(f"{where}: unknown ph {ph!r}")
            continue
        if ph == "M":
            if seen_non_meta:
                errors.append(f"{where}: metadata after timeline events")
            if e.get("name") == "process_name" and isinstance(
                    e.get("pid"), int):
                if not (e.get("args") or {}).get("name"):
                    errors.append(f"{where}: empty process_name for "
                                  f"pid={e['pid']}")
                named_pids.add(e["pid"])
            continue
        seen_non_meta = True
        pid, tid, ts = e.get("pid"), e.get("tid"), e.get("ts")
        if not isinstance(pid, int) or not isinstance(tid, int):
            errors.append(f"{where}: non-integer pid/tid ({pid!r}/{tid!r})")
            continue
        if not isinstance(ts, (int, float)) or ts < 0:
            errors.append(f"{where}: bad ts {ts!r}")
            continue
        event_pids.add(pid)
        track = (pid, tid)
        prev = last_ts.get(track)
        if prev is not None and ts < prev:
            errors.append(
                f"{where}: ts goes backwards on track pid={pid} tid={tid} "
                f"({ts} < {prev})")
        last_ts[track] = ts
        name = e.get("name")
        if ph == "C":
            # counter tracks share a tid but each NAME is its own series:
            # per-series ts must be monotone and every value numeric
            ctrack = (pid, tid, name)
            cprev = counter_ts.get(ctrack)
            if cprev is not None and ts < cprev:
                errors.append(
                    f"{where}: counter {name!r} ts goes backwards on "
                    f"pid={pid} tid={tid} ({ts} < {cprev})")
            counter_ts[ctrack] = ts
            for k, v in (e.get("args") or {}).items():
                if not isinstance(v, (int, float)):
                    errors.append(
                        f"{where}: counter {name!r} arg {k!r} is "
                        f"non-numeric ({v!r})")
        if ph in ("s", "t", "f"):
            # request-trace flow events (obs/export.py flow_events):
            # collected here, invariants checked after the pass — an id
            # must open with s, close with f, and every event must bind
            # to an enclosing X slice on its (pid, tid) track
            fid = e.get("id")
            if fid is None:
                errors.append(f"{where}: flow event without an id")
            else:
                flow_events.setdefault(fid, []).append(
                    (ph, ts, pid, tid, where))
            continue
        if ph == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append(f"{where}: X event with bad dur {dur!r}")
            else:
                slices.setdefault(track, []).append((ts, ts + dur))
            base = (name or "").split("#", 1)[0]
            if base in ANNOTATION_NAMES and "#" not in (name or "") \
                    and not e.get("args"):
                errors.append(
                    f"{where}: annotation {base!r} carries no ids "
                    "(no args, no TraceMe-encoded metadata)")
        elif ph == "B":
            begin_stack.setdefault(track, []).append(name)
        elif ph == "E":
            stack = begin_stack.get(track)
            if not stack:
                errors.append(
                    f"{where}: E without matching B on track pid={pid} "
                    f"tid={tid}")
            else:
                stack.pop()
    for (pid, tid), stack in begin_stack.items():
        for name in stack:
            errors.append(
                f"unclosed B event {name!r} on track pid={pid} tid={tid}")
    for fid, evs in sorted(flow_events.items(), key=lambda kv: str(kv[0])):
        evs.sort(key=lambda e: e[1])
        phs = [e[0] for e in evs]
        if phs.count("s") != 1:
            errors.append(f"flow id {fid}: {phs.count('s')} start (s) "
                          f"events (need exactly 1)")
        elif phs[0] != "s":
            errors.append(f"flow id {fid}: does not open with s "
                          f"(opens {phs[0]!r})")
        if phs.count("f") != 1:
            errors.append(f"flow id {fid}: {phs.count('f')} finish (f) "
                          f"events — a dangling flow never terminates")
        elif phs[-1] != "f":
            errors.append(f"flow id {fid}: f is not the final event")
        for ph, ts, pid, tid, where in evs:
            track_slices = slices.get((pid, tid), ())
            if not any(s0 <= ts <= s1 for s0, s1 in track_slices):
                errors.append(
                    f"{where}: flow {ph!r} id {fid} has no enclosing X "
                    f"slice on pid={pid} tid={tid} at ts={ts} (binding "
                    f"endpoint missing)")
    for pid in sorted(event_pids - named_pids):
        errors.append(f"pid={pid} carries timeline events but no "
                      "process_name metadata (unnamed track group)")
    return errors


def validate_file(path):
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        return [f"cannot load {path}: {e}"]
    events = data.get("traceEvents") if isinstance(data, dict) else data
    if events is None:
        return [f"{path}: no traceEvents key"]
    return validate_events(events)


def _self_test():
    """End-to-end: armed two-controller run → merged export → validate."""
    import os
    import tempfile

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # runnable from anywhere: the repo root is this script's parent
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)

    from hyperopt_tpu import hp
    from hyperopt_tpu.obs import ObsConfig, RunObs
    from hyperopt_tpu.obs import report
    from hyperopt_tpu.obs.health import controller_stream_path
    from hyperopt_tpu.parallel.driver import fmin_multihost

    space = {"x": hp.uniform("x", -5, 5)}
    with tempfile.TemporaryDirectory() as d:
        base = os.path.join(d, "run.jsonl")
        streams = []
        # two controllers' streams, exactly as a 2-process fmin_multihost
        # names them (run.p0.jsonl / run.p1.jsonl, run_id tagged -p<i>)
        for pidx in range(2):
            path = controller_stream_path(base, pidx)
            obs = RunObs(ObsConfig(level="trace", jsonl_path=path),
                         run_id=f"mh-p{pidx}")
            fmin_multihost(lambda s: (s["x"] - 1.0) ** 2, space,
                           max_evals=4, batch=2, seed=0, obs=obs,
                           _force_single=True)
            streams.append(path)
        out = os.path.join(d, "trace.json")
        rc = report.main(["--export-trace", out] + streams)
        if rc != 0:
            print("self-test: --export-trace failed", file=sys.stderr)
            return 1
        errors = validate_file(out)
        if errors:
            print("self-test: exported trace is INVALID:", file=sys.stderr)
            for e in errors:
                print("  " + e, file=sys.stderr)
            return 1
        with open(out) as f:
            events = json.load(f)["traceEvents"]
        n_groups = len({e.get("pid") for e in events})
        if n_groups != len(streams):
            print(f"self-test: expected {len(streams)} process track "
                  f"groups, got {n_groups}", file=sys.stderr)
            return 1
        print(f"self-test OK: {len(events)} events across {n_groups} "
              "controller track groups validate")
        return 0


_PROFILE_CHILD = r"""
import os, sys, time
import numpy as np
from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu.algos import rand

url_file, stream, cap_dir, stop_file = sys.argv[1:5]
t = Trials()

state = {"written": False}
def objective(d):
    if not state["written"]:
        with open(url_file + ".tmp", "w") as f:
            f.write(t.obs_http_url or "DISABLED")
        os.replace(url_file + ".tmp", url_file)
        state["written"] = True
    time.sleep(0.05)
    # the run stays demonstrably live until the parent finished its
    # capture: the stop file flips the loss under loss_threshold
    if os.path.exists(stop_file):
        return -1.0
    return 1.0 + (d["x"] - 1.0) ** 2

fmin(objective, {"x": hp.uniform("x", -5, 5)}, algo=rand.suggest,
     max_evals=100000, loss_threshold=0.0, trials=t,
     rstate=np.random.default_rng(0), show_progressbar=False,
     obs=stream, obs_http=0, profile=cap_dir)
print("CHILD_DONE")
"""


def _profile_self_test():
    """The device-capture round trip: ``/profile?sec=1`` against a live
    CPU-backend run, then the capture must merge with the host spans into
    a trace that validates — including the device track-group naming and
    annotation-id lint."""
    import os
    import subprocess
    import tempfile
    import time
    import urllib.request

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    with tempfile.TemporaryDirectory() as d:
        url_file = os.path.join(d, "url")
        stream = os.path.join(d, "run.jsonl")
        cap_dir = os.path.join(d, "captures")
        stop_file = os.path.join(d, "stop")
        proc = subprocess.Popen(
            [sys.executable, "-c", _PROFILE_CHILD, url_file, stream,
             cap_dir, stop_file],
            env=env, cwd=repo, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True)
        try:
            deadline = time.time() + 180
            while not os.path.exists(url_file):
                if proc.poll() is not None or time.time() > deadline:
                    out, err = proc.communicate(timeout=10)
                    print("profile self-test: child died before serving:\n"
                          + err[-2000:], file=sys.stderr)
                    return 1
                time.sleep(0.05)
            with open(url_file) as f:
                url = f.read().strip()
            if url == "DISABLED":
                print("profile self-test: scrape server failed open",
                      file=sys.stderr)
                return 1
            # the on-demand capture, against the demonstrably live run
            # (bounded 1s record time; the xplane->trace conversion on
            # stop can take a while on a cold backend, hence the generous
            # HTTP timeout — the run keeps ticking throughout)
            try:
                with urllib.request.urlopen(url + "/profile?sec=1",
                                            timeout=300) as r:
                    cap = json.loads(r.read().decode())
            except Exception as e:
                print(f"profile self-test: /profile request failed: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)
                return 1
            if not cap.get("ok"):
                print("profile self-test: /profile failed: "
                      f"{cap.get('error')}", file=sys.stderr)
                return 1
            if not cap.get("trace_json") or not os.path.exists(
                    cap["trace_json"]):
                print("profile self-test: capture produced no "
                      f"trace.json.gz artifact under {cap.get('dir')}",
                      file=sys.stderr)
                return 1
            # capture landed: let the child finish its run cleanly
            with open(stop_file, "w") as f:
                f.write("done")
            out, err = proc.communicate(timeout=180)
            if "CHILD_DONE" not in out:
                print("profile self-test: child did not finish cleanly:\n"
                      + err[-2000:], file=sys.stderr)
                return 1
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()

        from hyperopt_tpu.obs import report

        merged = os.path.join(d, "merged_trace.json")
        rc = report.main(["--export-trace", merged, stream])
        if rc != 0:
            print("profile self-test: --export-trace failed",
                  file=sys.stderr)
            return 1
        errors = validate_file(merged)
        if errors:
            print("profile self-test: merged trace INVALID:",
                  file=sys.stderr)
            for e in errors:
                print("  " + e, file=sys.stderr)
            return 1
        with open(merged) as f:
            events = json.load(f)["traceEvents"]
        from hyperopt_tpu.obs.export import DEVICE_PID_BASE

        device_pids = {e["pid"] for e in events
                       if e.get("ph") != "M"
                       and e.get("pid", 0) >= DEVICE_PID_BASE}
        if not device_pids:
            print("profile self-test: merged trace has no device track "
                  "group — the capture artifact was not folded in",
                  file=sys.stderr)
            return 1
        n_dev = sum(1 for e in events
                    if e.get("pid", 0) >= DEVICE_PID_BASE
                    and e.get("ph") != "M")
        print(f"profile self-test OK: {len(events)} events, {n_dev} from "
              f"{len(device_pids)} device track group(s), lint clean")
        return 0


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python scripts/validate_trace.py",
        description="Validate Chrome/Perfetto trace-event JSON.")
    p.add_argument("traces", nargs="*", help="trace JSON file(s) to check")
    p.add_argument("--self-test", action="store_true",
                   help="generate a merged two-controller run end-to-end "
                        "and validate its export (the CI gate)")
    p.add_argument("--profile-self-test", action="store_true",
                   help="end-to-end device-capture round trip: "
                        "/profile?sec=1 against a live CPU run, merge the "
                        "artifact with the host spans, validate (the "
                        "PROFILE_GATE)")
    args = p.parse_args(argv)
    if args.self_test:
        return _self_test()
    if args.profile_self_test:
        return _profile_self_test()
    if not args.traces:
        p.error("give trace file(s) or --self-test")
    rc = 0
    for path in args.traces:
        errors = validate_file(path)
        if errors:
            rc = 1
            print(f"{path}: INVALID")
            for e in errors:
                print("  " + e)
        else:
            print(f"{path}: ok")
    return rc


if __name__ == "__main__":
    sys.exit(main())
