#!/usr/bin/env python
"""Validate Chrome/Perfetto trace-event JSON emitted by ``obs.report
--export-trace`` (hyperopt_tpu/obs/export.py).

Checked invariants — the contract a trace viewer actually relies on:

* top level is ``{"traceEvents": [...]}`` (object form) or a bare event
  array;
* every event is an object with a known ``ph`` (``X i B E M C``);
* non-metadata events carry numeric ``ts`` >= 0 and integer ``pid``/``tid``;
* complete (``X``) events have ``dur`` >= 0;
* duration ``B``/``E`` events are matched per ``(pid, tid)`` track (no
  dangling begin, no end-without-begin);
* per ``(pid, tid)`` track, non-metadata events appear in non-decreasing
  ``ts`` file order (the exporter sorts; a violation means a broken merge);
* metadata (``M``) events precede all others (the exporter's layout).

Exit 0 when every input validates, 1 otherwise, 2 on unreadable input.

``--self-test`` runs the whole pipeline end to end on CPU: a tiny armed
two-controller run (the ``fmin_multihost`` per-controller stream naming),
``obs.report --export-trace`` over the merged streams, then validation —
the opt-in CI gate ``TRACE_GATE=1 ./run_tests.sh`` wires this in next to
``bench_gate.py``.
"""

from __future__ import annotations

import argparse
import json
import sys

_KNOWN_PH = {"X", "i", "I", "B", "E", "M", "C"}


def validate_events(events):
    """Return a list of human-readable violations (empty = valid)."""
    errors = []
    if not isinstance(events, list):
        return ["traceEvents is not a list"]
    last_ts = {}  # (pid, tid) -> last seen ts
    begin_stack = {}  # (pid, tid) -> [names]
    seen_non_meta = False
    for i, e in enumerate(events):
        where = f"event[{i}]"
        if not isinstance(e, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = e.get("ph")
        if ph not in _KNOWN_PH:
            errors.append(f"{where}: unknown ph {ph!r}")
            continue
        if ph == "M":
            if seen_non_meta:
                errors.append(f"{where}: metadata after timeline events")
            continue
        seen_non_meta = True
        pid, tid, ts = e.get("pid"), e.get("tid"), e.get("ts")
        if not isinstance(pid, int) or not isinstance(tid, int):
            errors.append(f"{where}: non-integer pid/tid ({pid!r}/{tid!r})")
            continue
        if not isinstance(ts, (int, float)) or ts < 0:
            errors.append(f"{where}: bad ts {ts!r}")
            continue
        track = (pid, tid)
        prev = last_ts.get(track)
        if prev is not None and ts < prev:
            errors.append(
                f"{where}: ts goes backwards on track pid={pid} tid={tid} "
                f"({ts} < {prev})")
        last_ts[track] = ts
        if ph == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append(f"{where}: X event with bad dur {dur!r}")
        elif ph == "B":
            begin_stack.setdefault(track, []).append(e.get("name"))
        elif ph == "E":
            stack = begin_stack.get(track)
            if not stack:
                errors.append(
                    f"{where}: E without matching B on track pid={pid} "
                    f"tid={tid}")
            else:
                stack.pop()
    for (pid, tid), stack in begin_stack.items():
        for name in stack:
            errors.append(
                f"unclosed B event {name!r} on track pid={pid} tid={tid}")
    return errors


def validate_file(path):
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        return [f"cannot load {path}: {e}"]
    events = data.get("traceEvents") if isinstance(data, dict) else data
    if events is None:
        return [f"{path}: no traceEvents key"]
    return validate_events(events)


def _self_test():
    """End-to-end: armed two-controller run → merged export → validate."""
    import os
    import tempfile

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # runnable from anywhere: the repo root is this script's parent
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)

    from hyperopt_tpu import hp
    from hyperopt_tpu.obs import ObsConfig, RunObs
    from hyperopt_tpu.obs import report
    from hyperopt_tpu.obs.health import controller_stream_path
    from hyperopt_tpu.parallel.driver import fmin_multihost

    space = {"x": hp.uniform("x", -5, 5)}
    with tempfile.TemporaryDirectory() as d:
        base = os.path.join(d, "run.jsonl")
        streams = []
        # two controllers' streams, exactly as a 2-process fmin_multihost
        # names them (run.p0.jsonl / run.p1.jsonl, run_id tagged -p<i>)
        for pidx in range(2):
            path = controller_stream_path(base, pidx)
            obs = RunObs(ObsConfig(level="trace", jsonl_path=path),
                         run_id=f"mh-p{pidx}")
            fmin_multihost(lambda s: (s["x"] - 1.0) ** 2, space,
                           max_evals=4, batch=2, seed=0, obs=obs,
                           _force_single=True)
            streams.append(path)
        out = os.path.join(d, "trace.json")
        rc = report.main(["--export-trace", out] + streams)
        if rc != 0:
            print("self-test: --export-trace failed", file=sys.stderr)
            return 1
        errors = validate_file(out)
        if errors:
            print("self-test: exported trace is INVALID:", file=sys.stderr)
            for e in errors:
                print("  " + e, file=sys.stderr)
            return 1
        with open(out) as f:
            events = json.load(f)["traceEvents"]
        n_groups = len({e.get("pid") for e in events})
        if n_groups != len(streams):
            print(f"self-test: expected {len(streams)} process track "
                  f"groups, got {n_groups}", file=sys.stderr)
            return 1
        print(f"self-test OK: {len(events)} events across {n_groups} "
              "controller track groups validate")
        return 0


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python scripts/validate_trace.py",
        description="Validate Chrome/Perfetto trace-event JSON.")
    p.add_argument("traces", nargs="*", help="trace JSON file(s) to check")
    p.add_argument("--self-test", action="store_true",
                   help="generate a merged two-controller run end-to-end "
                        "and validate its export (the CI gate)")
    args = p.parse_args(argv)
    if args.self_test:
        return _self_test()
    if not args.traces:
        p.error("give trace file(s) or --self-test")
    rc = 0
    for path in args.traces:
        errors = validate_file(path)
        if errors:
            rc = 1
            print(f"{path}: INVALID")
            for e in errors:
                print("  " + e)
        else:
            print(f"{path}: ok")
    return rc


if __name__ == "__main__":
    sys.exit(main())
