"""PROBE_GATE end-to-end smoke: a REAL 2-replica subprocess fleet with
the blackbox prober armed on every replica, tenant traffic running
throughout, and a deterministic proposal-corruption fault on exactly
one replica — caught by golden-stream divergence within bounded probe
cycles, with the sealed ledger, the evidence bundle, the probe SLO
burn and the drain contract all checked from the outside.

What it pins (the audit contract no unit test can):

* phase 1 — **steady state is green and free**: two clean replicas,
  each self-probing over its real bound URL (``--probe on``), plus an
  out-of-process auditor prober cross-checking BOTH replicas' canary
  streams bitwise per cycle.  Every in-server prober must go green,
  the auditor must see zero divergence and burn zero probe SLO
  budget, ``/metrics`` must pass the probe-family exposition lint on
  both replicas, every verdict ledger line must be CRC-sealed, and
  the concurrent tenant studies must finish with exactly their budget
  of trials and zero pending — canary traffic stole nothing.

* phase 2 — **corruption is caught, bounded, and evidenced**: replica
  r1 is drained (SIGTERM → exit 0 — the restart-gate contract) and
  relaunched with ``corrupt@tick:1.0`` chaos silently perturbing one
  float per proposal row.  The auditor's cross-replica check must
  render a ``mismatch`` verdict within 3 cycles, burn the
  ``probe_golden_match`` SLO budget (and NOT ``probe_avail`` — the
  replica answers fine, it answers *wrong*), write a readable
  evidence bundle naming the diverging digests, and seal the red
  verdict into its ledger.  r1's own in-server prober must also turn
  red on ``GET /probes``.  Tenant traffic on the clean replica rides
  through it all with zero lost tells, and both replicas still drain
  to exit 0.

Opt in via ``PROBE_GATE=1 ./run_tests.sh``.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "scripts"))

from validate_scrape import PROBE_FAMILIES, validate_probe_families  # noqa: E402

PROBE_PERIOD = 2.0


def _env(chaos=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("HYPEROPT_TPU_CHAOS", None)
    env.pop("HYPEROPT_TPU_PROBE", None)
    if chaos:
        env["HYPEROPT_TPU_CHAOS"] = chaos
    return env


def _launch(store, port="0", chaos=None):
    proc = subprocess.Popen(
        [sys.executable, "-m", "hyperopt_tpu.service.server",
         "--announce", "--port", str(port), "--store", store,
         "--probe", "on", "--probe-period", str(PROBE_PERIOD)],
        cwd=_REPO, env=_env(chaos=chaos), stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True)
    deadline = time.monotonic() + 180
    url = None
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.startswith("SERVICE_URL "):
            url = line.split(None, 1)[1].strip()
            break
        if proc.poll() is not None:
            break
    return proc, url


def _get(url, path, timeout=20):
    with urllib.request.urlopen(url + path, timeout=timeout) as r:
        return r.status, r.read().decode("utf-8")


def _get_json(url, path, timeout=20):
    code, body = _get(url, path, timeout=timeout)
    return code, json.loads(body)


def _sigterm_drain(proc, label):
    """SIGTERM → drain → exit 0: the restart-gate contract."""
    if proc.poll() is not None:
        print(f"{label}: FAIL — replica died early "
              f"(rc {proc.returncode})", file=sys.stderr)
        return False
    proc.send_signal(signal.SIGTERM)
    try:
        rc = proc.wait(timeout=90)
    except subprocess.TimeoutExpired:
        proc.kill()
        print(f"{label}: FAIL — replica ignored SIGTERM", file=sys.stderr)
        return False
    if rc != 0:
        print(f"{label}: FAIL — drain exited {rc}, want 0",
              file=sys.stderr)
        return False
    return True


class _TenantDriver(threading.Thread):
    """One tenant study riding alongside the canaries: create →
    budget x (ask → tell), then assert nothing was lost."""

    def __init__(self, url, seed, budget=8, n_startup=3):
        super().__init__()
        self.url = url
        self.seed = seed
        self.budget = budget
        self.n_startup = n_startup
        self.study_id = None
        self.told = 0
        self.error = None

    def run(self):
        from hyperopt_tpu.service import ServiceClient

        try:
            client = ServiceClient([self.url], key=self.seed, timeout=60)
            sid = client.create_study(
                space={"x": {"dist": "uniform", "args": [-5, 5]}},
                seed=self.seed, n_startup_jobs=self.n_startup)
            for _ in range(self.budget):
                t = client.ask(sid)[0]
                client.tell(sid, t["tid"],
                            float((t["params"]["x"] - 1.0) ** 2))
                self.told += 1
            self.study_id = sid
        except Exception as e:  # noqa: BLE001
            self.error = f"tenant@{self.url}: {type(e).__name__}: {e}"


def _check_tenants(drivers, label):
    errors = [d.error for d in drivers if d.error]
    if errors:
        print(f"{label}: FAIL — tenant errors: {errors}", file=sys.stderr)
        return False
    lost = []
    for d in drivers:
        _, table = _get_json(d.url, "/studies")
        s = {s["study_id"]: s for s in table["studies"]}.get(d.study_id)
        if s is None or s["n_trials"] != d.budget or s["n_pending"]:
            lost.append((d.study_id,
                         s and s["n_trials"], s and s["n_pending"]))
    if lost:
        print(f"{label}: FAIL — lost/duplicated tenant tells: {lost}",
              file=sys.stderr)
        return False
    print(f"{label}: {len(drivers)} tenant studies complete, "
          "zero lost tells")
    return True


def _wait_probe_green(url, label, timeout=120):
    """The in-server prober must go green: newest verdict ok, fresh.
    Early ``error`` cycles (cold-compile timeouts) are the fail-open
    contract working, not a failure — we wait through them."""
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            _, p = _get_json(url, "/probes")
        except Exception:  # noqa: BLE001 - server mid-cycle
            time.sleep(0.5)
            continue
        last = p
        if p.get("armed") and p.get("green") and p.get("cycles", 0) >= 2:
            return p
        time.sleep(0.5)
    print(f"{label}: FAIL — prober never went green: "
          f"{json.dumps(last)[:400]}", file=sys.stderr)
    return None


def _check_sealed_ledger(store, label, want_verdict="ok"):
    from hyperopt_tpu.obs.prober import probes_path_for, read_probes

    path = probes_path_for(store, "single")
    if not os.path.exists(path):
        print(f"{label}: FAIL — no verdict ledger at {path}",
              file=sys.stderr)
        return False
    recs, corrupt, torn = read_probes(path)
    if corrupt:
        print(f"{label}: FAIL — {corrupt} corrupt ledger lines in "
              f"{path}", file=sys.stderr)
        return False
    if not any(r.get("verdict") == want_verdict for r in recs):
        print(f"{label}: FAIL — no {want_verdict!r} verdict in {path} "
              f"({[r.get('verdict') for r in recs]})", file=sys.stderr)
        return False
    return True


def _lint_metrics(url, label):
    code, text = _get(url, "/metrics")
    if code != 200:
        print(f"{label}: FAIL — /metrics {code}", file=sys.stderr)
        return False
    errors = validate_probe_families(text)
    if errors:
        print(f"{label}: FAIL — probe exposition lint: {errors}",
              file=sys.stderr)
        return False
    missing = [f for f in PROBE_FAMILIES if f not in text]
    if missing:
        print(f"{label}: FAIL — /metrics missing probe families "
              f"{missing}", file=sys.stderr)
        return False
    return True


def _auditor(urls, ledger):
    """The out-of-process cross-replica prober: generous per-request
    timeout (subprocess replicas cold-compile), its own SLO plane."""
    from hyperopt_tpu.obs.prober import Prober
    from hyperopt_tpu.obs.slo import PROBE_TARGETS, SLOPlane

    plane = SLOPlane()
    for name, spec in PROBE_TARGETS.items():
        plane.add_objective(name, spec)
    # the wide period buys a wide cycle deadline: a freshly relaunched
    # replica cold-compiles its first canary ask, and a deadline miss
    # reads as `error` where the check wants a clean mismatch verdict
    return Prober(urls, period=30.0, slo=plane,
                  ledger_path=ledger, replica="auditor",
                  request_timeout=30.0, escalation_cooldown=0.0), plane


def phase1_steady_green():
    print("probe_smoke: phase 1 — 2 clean replicas, every prober green, "
          "canary traffic free")
    with tempfile.TemporaryDirectory() as root:
        stores = [os.path.join(root, "r0"), os.path.join(root, "r1")]
        procs, urls = [], []
        for store in stores:
            proc, url = _launch(store)
            if url is None:
                print("phase1: FAIL — replica never announced",
                      file=sys.stderr)
                return 1
            procs.append(proc)
            urls.append(url)
        try:
            drivers = [_TenantDriver(u, seed=100 + i, budget=8)
                       for i, u in enumerate(urls)]
            for d in drivers:
                d.start()
            for i, url in enumerate(urls):
                if _wait_probe_green(url, f"phase1 r{i}") is None:
                    return 1
            # the auditor: both canary streams must agree bitwise
            aud, plane = _auditor(urls, os.path.join(root, "aud.jsonl"))
            for cyc in range(2):
                rec = aud.run_cycle()
                if rec["verdict"] != "ok" or rec["diverged"]:
                    print(f"phase1: FAIL — auditor cycle {cyc + 1} "
                          f"{rec['verdict']} diverged={rec['diverged']}",
                          file=sys.stderr)
                    return 1
            g = plane.status()["probe_golden_match"]
            if g["budget_remaining_frac"] < 1.0:
                print("phase1: FAIL — clean fleet burned golden-match "
                      "budget", file=sys.stderr)
                return 1
            for d in drivers:
                d.join()
            if not _check_tenants(drivers, "phase1"):
                return 1
            for i, (url, store) in enumerate(zip(urls, stores)):
                if not _lint_metrics(url, f"phase1 r{i}"):
                    return 1
                if not _check_sealed_ledger(store, f"phase1 r{i}"):
                    return 1
            for i, proc in enumerate(procs):
                if not _sigterm_drain(proc, f"phase1 r{i}"):
                    return 1
            print("phase1: PASS — both replicas green, auditor saw zero "
                  "divergence, ledgers sealed, tenants whole, "
                  "drains exit 0")
            return 0
        finally:
            for proc in procs:
                if proc.poll() is None:
                    proc.kill()
                    proc.wait()


def phase2_divergence_caught():
    print("probe_smoke: phase 2 — corrupt one replica's proposal "
          "stream; the prober catches it within 3 cycles")
    with tempfile.TemporaryDirectory() as root:
        stores = [os.path.join(root, "r0"), os.path.join(root, "r1")]
        procs, urls = [], []
        # r0 clean; r1 launches clean too, goes green, then is drained
        # and relaunched with every proposal row silently perturbed
        for store in stores:
            proc, url = _launch(store)
            if url is None:
                print("phase2: FAIL — replica never announced",
                      file=sys.stderr)
                return 1
            procs.append(proc)
            urls.append(url)
        try:
            for i, url in enumerate(urls):
                if _wait_probe_green(url, f"phase2 r{i}") is None:
                    return 1
            # the restart-gate drain contract, then the fault
            if not _sigterm_drain(procs[1], "phase2 r1"):
                return 1
            port = urls[1].rsplit(":", 1)[1]
            procs[1], urls[1] = _launch(stores[1], port=port,
                                        chaos="7:corrupt@tick:1.0")
            if urls[1] is None:
                print("phase2: FAIL — corrupted r1 never announced",
                      file=sys.stderr)
                return 1
            drivers = [_TenantDriver(urls[0], seed=200, budget=8)]
            drivers[0].start()
            # the auditor must catch the divergence within 3 cycles
            aud, plane = _auditor(urls, os.path.join(root, "aud.jsonl"))
            caught = None
            for cyc in range(1, 4):
                rec = aud.run_cycle()
                if rec["verdict"] == "mismatch":
                    caught = cyc
                    break
            if caught is None:
                print("phase2: FAIL — 3 auditor cycles, no mismatch "
                      f"verdict (last: {aud.last})", file=sys.stderr)
                return 1
            print(f"phase2: auditor caught the divergence at cycle "
                  f"{caught}/3")
            st = plane.status()
            if st["probe_golden_match"]["budget_remaining_frac"] >= 1.0:
                print("phase2: FAIL — mismatch burned no golden-match "
                      "budget", file=sys.stderr)
                return 1
            if st["probe_avail"]["budget_remaining_frac"] < 1.0:
                print("phase2: FAIL — mismatch burned probe_avail (the "
                      "replica answered; it answered WRONG)",
                      file=sys.stderr)
                return 1
            if not aud.evidence_bundles:
                print("phase2: FAIL — no evidence bundle written",
                      file=sys.stderr)
                return 1
            bpath = os.path.join(aud.evidence_bundles[-1], "bundle.json")
            with open(bpath, encoding="utf-8") as f:
                bundle = json.load(f)
            for key in ("verdict", "digest", "golden", "responses",
                        "timeline"):
                if key not in bundle:
                    print(f"phase2: FAIL — evidence bundle missing "
                          f"{key!r}: {bpath}", file=sys.stderr)
                    return 1
            from hyperopt_tpu.obs.prober import read_probes

            recs, corrupt, _ = read_probes(os.path.join(root,
                                                        "aud.jsonl"))
            if corrupt or not any(r.get("verdict") == "mismatch"
                                  for r in recs):
                print("phase2: FAIL — auditor ledger unsealed or "
                      "missing the red verdict", file=sys.stderr)
                return 1
            # r1's own in-server prober must also turn red
            deadline = time.monotonic() + 120
            red = None
            while time.monotonic() < deadline:
                try:
                    _, p = _get_json(urls[1], "/probes")
                except Exception:  # noqa: BLE001
                    time.sleep(0.5)
                    continue
                if p.get("verdicts", {}).get("mismatch", 0) >= 1:
                    red = p
                    break
                time.sleep(0.5)
            if red is None:
                print("phase2: FAIL — r1's in-server prober never "
                      "rendered mismatch", file=sys.stderr)
                return 1
            if red.get("green"):
                print("phase2: FAIL — r1 /probes still green after "
                      "mismatch", file=sys.stderr)
                return 1
            drivers[0].join()
            if not _check_tenants(drivers, "phase2"):
                return 1
            for i, proc in enumerate(procs):
                if not _sigterm_drain(proc, f"phase2 r{i}"):
                    return 1
            print("phase2: PASS — mismatch in "
                  f"{caught} cycle(s), golden-match SLO burned, "
                  "evidence bundle readable, tenants whole, "
                  "drains exit 0")
            return 0
        finally:
            for proc in procs:
                if proc is not None and proc.poll() is None:
                    proc.kill()
                    proc.wait()


def main():
    for phase in (phase1_steady_green, phase2_divergence_caught):
        rc = phase()
        if rc:
            return rc
    print("probe_smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
