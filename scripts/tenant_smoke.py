"""TENANT_GATE end-to-end smoke (ISSUE 20): the tenant observatory over
a REAL subprocess ask/tell server under a ~10:1 adversarial tenant mix.

What it pins (the multi-tenant serving contract no unit test can):

* a light tenant and a noisy tenant (6 hammer threads over 4 studies)
  share one server; the light tenant's ask p99 stays bounded relative
  to its own solo baseline (the DRR wave packer + per-tenant admission
  budget are what hold the line);
* the noisy tenant trips its per-tenant ask budget and gets typed
  per-tenant 429s WITH a ``Retry-After`` header, while the light tenant
  sees zero sheds;
* ``GET /tenants`` serves the bounded attribution table with both
  tenants and the noisy tenant dominating device time; ``/studies``
  rows carry the tenant column; ``/metrics`` passes the exposition lint
  INCLUDING the ``hyperopt_tpu_service_tenant_*`` roll-up families
  (``validate_scrape.py --require-tenant`` contract);
* probe traffic (``x-probe: 1``) never mints a tenant row — the same
  exclusion the tenant SLOs apply;
* zero tells are lost: every driven study ends with exactly its told
  count and nothing pending;
* the server drains cleanly on SIGTERM (exit 0).

Opt in via ``TENANT_GATE=1 ./run_tests.sh``.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

SPEC = {"x": {"dist": "uniform", "args": [-5, 5]}}
N_NOISY_STUDIES = 4
N_NOISY_THREADS = 4
WARM_ROUNDS = 70          # drives the shared cohort past the 64-cap widen
SOLO_SAMPLE = 20          # solo p99: separate post-warm window, no widen
MIXED_ROUNDS = 30
TENANT_QUOTA = 2


def _post(url, path, body, tenant=None, probe=False, timeout=60):
    """(status, payload, headers) — typed errors returned, not raised."""
    headers = {"Content-Type": "application/json"}
    if tenant is not None:
        headers["x-tenant"] = tenant
    if probe:
        headers["x-probe"] = "1"
    req = urllib.request.Request(url + path, data=json.dumps(body).encode(),
                                 headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read())
        except Exception:  # noqa: BLE001
            payload = {}
        return e.code, payload, dict(e.headers)


def _get(url, path, timeout=60):
    with urllib.request.urlopen(url + path, timeout=timeout) as r:
        body = r.read()
    return body.decode() if path == "/metrics" else json.loads(body)


def _ask_tell(url, sid, tenant, stats, lock, lat=None):
    """One ask+tell round; 429s recorded with their Retry-After hint
    honored (a shed client that spins instead of backing off is just a
    second DoS), successful asks ALWAYS told (retrying the tell) so no
    tell is ever lost to the mix."""
    t0 = time.perf_counter()
    code, a, headers = _post(url, "/ask", {"study_id": sid}, tenant=tenant)
    if lat is not None and code == 200:
        lat.append(time.perf_counter() - t0)
    if code == 429:
        ra = headers.get("Retry-After")
        with lock:
            stats.setdefault(f"{tenant}_429", []).append(
                (a.get("error", ""), ra))
        try:
            time.sleep(min(float(ra), 0.5))
        except (TypeError, ValueError):
            time.sleep(0.05)
        return False
    if code != 200:
        with lock:
            stats.setdefault("errors", []).append((tenant, code, a))
        return False
    tid = a["trials"][0]["tid"]
    loss = float(a["trials"][0]["params"]["x"] ** 2)
    for _ in range(20):
        code, _t, _h = _post(url, "/tell", {"study_id": sid, "tid": tid,
                                            "loss": loss}, tenant=tenant)
        if code == 200:
            with lock:
                stats[sid] = stats.get(sid, 0) + 1
            return True
        time.sleep(0.1)
    with lock:
        stats.setdefault("errors", []).append((tenant, "tell-failed", sid))
    return False


def _p99(lat):
    lat = sorted(lat)
    return lat[min(len(lat) - 1, int(0.99 * len(lat)))]


def main():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("HYPEROPT_TPU_CHAOS", None)
    env.pop("HYPEROPT_TPU_TENANT", None)   # default ON is the pin
    env["HYPEROPT_TPU_TENANT_QUOTA"] = str(TENANT_QUOTA)
    proc = subprocess.Popen(
        [sys.executable, "-m", "hyperopt_tpu.service.server",
         "--port", "0", "--announce", "--max-studies", "64"],
        cwd=_REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    url = None
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if line.startswith("SERVICE_URL "):
                url = line.split(None, 1)[1].strip()
                break
            if proc.poll() is not None:
                break
        if url is None:
            print("tenant_smoke: FAIL — server never announced",
                  file=sys.stderr)
            print((proc.stderr.read() or "")[-2000:], file=sys.stderr)
            return 1
        print(f"tenant_smoke: server up at {url} (pid {proc.pid}, "
              f"per-tenant quota {TENANT_QUOTA})")

        stats, lock = {}, threading.Lock()

        # mint the census: one light study, N noisy studies, all on the
        # same space so every widen compile is shared cohort-cache work
        code, r, _h = _post(url, "/study", {
            "space": SPEC, "seed": 100, "n_startup_jobs": 2,
            "study_id": "light-0"}, tenant="light")
        assert code == 200, r
        light = r["study_id"]
        noisy = []
        for i in range(N_NOISY_STUDIES):
            code, r, _h = _post(url, "/study", {
                "space": SPEC, "seed": 200 + i, "n_startup_jobs": 2,
                "study_id": f"noisy-{i}"}, tenant="noisy")
            assert code == 200, r
            noisy.append(r["study_id"])

        # probe-exclusion pin: probe traffic must never mint a row (a
        # 404 ask still rides the full observe path, and no trial is
        # minted that would dirty the zero-lost-tells audit below)
        _post(url, "/ask", {"study_id": "probe-canary-target"},
              tenant="canary-bot", probe=True)

        # warm drive: push the shared cohort through its widen
        # boundaries (16/32/64 caps) so no jit compile lands inside
        # either measured window — every study shares the space, so the
        # cohort cache pays each shape exactly once, here
        t0 = time.perf_counter()
        for _ in range(WARM_ROUNDS):
            _ask_tell(url, light, "light", stats, lock)
        warm_sec = time.perf_counter() - t0
        # solo baseline: a separate post-warm window on cached shapes
        solo_lat = []
        for _ in range(SOLO_SAMPLE):
            _ask_tell(url, light, "light", stats, lock, lat=solo_lat)
        solo_p99 = _p99(solo_lat)
        print(f"tenant_smoke: solo baseline — warm {WARM_ROUNDS} rounds "
              f"in {warm_sec:.1f}s, light solo p99 "
              f"{solo_p99 * 1e3:.1f}ms over {SOLO_SAMPLE} rounds")

        # the ~10:1 adversarial window: hammer threads spin ask+tell on
        # the noisy tenant's studies while the light tenant keeps its
        # sequential cadence and measures its own tail.  The table is
        # cumulative, so dominance is judged on the window's DELTA.
        pre = {t: dict(row) for t, row in
               ((_get(url, "/tenants") or {}).get("table") or {}).items()}
        stop = threading.Event()

        def hammer(i):
            while not stop.is_set():
                _ask_tell(url, noisy[i % N_NOISY_STUDIES], "noisy",
                          stats, lock)

        threads = [threading.Thread(target=hammer, args=(i,), daemon=True)
                   for i in range(N_NOISY_THREADS)]
        for t in threads:
            t.start()
        # unmeasured prefix: the multi-study cohort stack is a NEW jit
        # shape (solo ticked one study, the mix ticks five) — let that
        # one-time compile land before the tail is scored
        for _ in range(5):
            _ask_tell(url, light, "light", stats, lock)
        mixed_lat = []
        for _ in range(MIXED_ROUNDS):
            _ask_tell(url, light, "light", stats, lock, lat=mixed_lat)
        stop.set()
        for t in threads:
            t.join(timeout=60)
        mixed_p99 = _p99(mixed_lat)
        noisy_sheds = stats.get("noisy_429", [])
        light_sheds = stats.get("light_429", [])
        print(f"tenant_smoke: adversarial window — light mixed p99 "
              f"{mixed_p99 * 1e3:.1f}ms ({len(mixed_lat)} asks), noisy "
              f"429s {len(noisy_sheds)}, light 429s {len(light_sheds)}")

        if stats.get("errors"):
            print(f"tenant_smoke: FAIL — hard errors in the mix: "
                  f"{stats['errors'][:5]}", file=sys.stderr)
            return 1
        if light_sheds:
            print(f"tenant_smoke: FAIL — the light tenant was shed "
                  f"{len(light_sheds)}x (quota {TENANT_QUOTA} should "
                  "never bind a sequential caller)", file=sys.stderr)
            return 1
        if not noisy_sheds:
            print("tenant_smoke: FAIL — the noisy tenant never tripped "
                  "its per-tenant ask budget", file=sys.stderr)
            return 1
        bad = [s for s in noisy_sheds if "ask budget" not in s[0]
               or not s[1]]
        if len(bad) == len(noisy_sheds):
            print(f"tenant_smoke: FAIL — noisy 429s lack the typed "
                  f"per-tenant error or Retry-After: {noisy_sheds[:3]}",
                  file=sys.stderr)
            return 1
        # bounded tail: ≤3x solo, with an absolute floor that absorbs
        # one stray scheduler hiccup on shared CI hardware
        bound = max(3.0 * solo_p99, 3.0)
        if mixed_p99 > bound:
            print(f"tenant_smoke: FAIL — light mixed p99 "
                  f"{mixed_p99:.3f}s > bound {bound:.3f}s "
                  f"(solo {solo_p99:.3f}s)", file=sys.stderr)
            return 1

        # the attribution surfaces
        ten = _get(url, "/tenants")
        table = (ten or {}).get("table") or {}
        if not ten.get("armed") or "light" not in table \
                or "noisy" not in table:
            print(f"tenant_smoke: FAIL — /tenants lacks the mix: {ten}",
                  file=sys.stderr)
            return 1
        if "canary-bot" in table:
            print("tenant_smoke: FAIL — probe traffic minted a tenant "
                  "row", file=sys.stderr)
            return 1
        def delta(t, key):
            return (table[t][key]
                    - (pre.get(t) or {}).get(key, 0))

        if delta("noisy", "asks") <= delta("light", "asks"):
            print(f"tenant_smoke: FAIL — the noisy tenant did not "
                  f"dominate the adversarial window: noisy "
                  f"+{delta('noisy', 'asks')} asks vs light "
                  f"+{delta('light', 'asks')}", file=sys.stderr)
            return 1
        if ten.get("sheds", 0) < len(noisy_sheds):
            print(f"tenant_smoke: FAIL — ledger sheds {ten.get('sheds')} "
                  f"< observed {len(noisy_sheds)}", file=sys.stderr)
            return 1

        from validate_scrape import validate_tenant_families

        errors = validate_tenant_families(_get(url, "/metrics"))
        if errors:
            print("tenant_smoke: FAIL — /metrics tenant lint:",
                  file=sys.stderr)
            for e in errors[:10]:
                print("  " + e, file=sys.stderr)
            return 1

        # tenant column on /studies + zero lost tells: every told round
        # is settled, nothing pending anywhere
        rows = {s["study_id"]: s
                for s in _get(url, "/studies").get("studies", [])}
        if rows[light].get("tenant") != "light" \
                or rows[noisy[0]].get("tenant") != "noisy":
            print(f"tenant_smoke: FAIL — /studies rows lack the tenant "
                  f"column: {rows[light]}", file=sys.stderr)
            return 1
        lost = []
        for sid in [light] + noisy:
            told = stats.get(sid, 0)
            s = rows.get(sid)
            if not s or s["n_trials"] != told or s["n_pending"]:
                lost.append((sid, told, s and s["n_trials"],
                             s and s["n_pending"]))
        if lost:
            print(f"tenant_smoke: FAIL — lost tells: {lost}",
                  file=sys.stderr)
            return 1
        total_tells = sum(stats.get(s, 0) for s in [light] + noisy)
        print(f"tenant_smoke: surfaces ok — /tenants table "
              f"{sorted(table)}, scrape lints, {total_tells} tells "
              "settled, zero pending")

        # clean SIGTERM drain
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
        if rc != 0:
            print(f"tenant_smoke: FAIL — SIGTERM exit {rc}",
                  file=sys.stderr)
            return 1
        print("tenant_smoke: PASS")
        return 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


if __name__ == "__main__":
    sys.exit(main())
